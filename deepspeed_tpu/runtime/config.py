"""DeepSpeedConfig: parse ds_config.json (or dict) into a typed config object.

Behavior-parity port of reference runtime/config.py:515-783 — same key surface,
batch-triangle completion (any two of train_batch_size /
train_micro_batch_size_per_gpu / gradient_accumulation_steps imply the third),
elasticity override, and sanity checks. TPU deltas:

- world size comes from the mesh/data-parallel size (``jax.device_count()``
  by default) instead of torch.distributed;
- a ``bf16`` block is accepted (TPU-native precision); ZeRO requires fp16 OR
  bf16 (the reference requires fp16, engine-side bf16 did not exist in 0.3.10);
- ZeRO stage 3 (parameter sharding) is allowed — GSPMD gives it naturally —
  while stages 1/2 keep reference semantics.
"""

import json

from deepspeed_tpu.elasticity import (
    ElasticityConfigError,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
)
from deepspeed_tpu.elasticity.constants import (
    ELASTICITY,
    IGNORE_NON_ELASTIC_BATCH_INFO,
    IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT,
)
from deepspeed_tpu.profiling.config import DeepSpeedFlopsProfilerConfig
from deepspeed_tpu.runtime.activation_checkpointing.config import (
    DeepSpeedActivationCheckpointingConfig,
)
from deepspeed_tpu.runtime.config_utils import (
    dict_raise_error_on_duplicate_keys,
    get_scalar_param,
)
from deepspeed_tpu.runtime.constants import *  # noqa: F401,F403
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.constants import (
    MAX_STAGE_ZERO_OPTIMIZATION,
    ZERO_OPTIMIZATION_GRADIENTS,
)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.version import version as __version__

TENSOR_CORE_ALIGN_SIZE = 8

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER,
    ADAMW_OPTIMIZER,
    LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER,
]


def get_amp_enabled(param_dict):
    if AMP in param_dict.keys():
        return get_scalar_param(param_dict[AMP], AMP_ENABLED, AMP_ENABLED_DEFAULT)
    return False


def get_amp_params(param_dict):
    if AMP in param_dict.keys():
        amp_params = dict(param_dict[AMP])
        amp_params.pop(AMP_ENABLED, None)
        return amp_params
    return False


def get_fp16_enabled(param_dict):
    if FP16 in param_dict.keys():
        return get_scalar_param(param_dict[FP16], FP16_ENABLED, FP16_ENABLED_DEFAULT)
    return False


def get_bfloat16_enabled(param_dict):
    if BFLOAT16 in param_dict.keys():
        return get_scalar_param(param_dict[BFLOAT16],
                                BFLOAT16_ENABLED,
                                BFLOAT16_ENABLED_DEFAULT)
    return False


def get_loss_scale(param_dict):
    if get_fp16_enabled(param_dict):
        return get_scalar_param(param_dict[FP16],
                                FP16_LOSS_SCALE,
                                FP16_LOSS_SCALE_DEFAULT)
    return FP16_LOSS_SCALE_DEFAULT


def get_initial_dynamic_scale(param_dict):
    if get_fp16_enabled(param_dict):
        initial_scale_power = get_scalar_param(param_dict[FP16],
                                               FP16_INITIAL_SCALE_POWER,
                                               FP16_INITIAL_SCALE_POWER_DEFAULT)
    else:
        initial_scale_power = FP16_INITIAL_SCALE_POWER_DEFAULT
    return 2 ** initial_scale_power


def get_dynamic_loss_scale_args(param_dict):
    loss_scale_args = None
    if get_fp16_enabled(param_dict):
        fp16_dict = param_dict[FP16]
        dynamic_props = [
            FP16_INITIAL_SCALE_POWER,
            FP16_LOSS_SCALE_WINDOW,
            FP16_MIN_LOSS_SCALE,
            FP16_HYSTERESIS,
        ]
        if any(prop in fp16_dict for prop in dynamic_props):
            init_scale = get_scalar_param(fp16_dict,
                                          FP16_INITIAL_SCALE_POWER,
                                          FP16_INITIAL_SCALE_POWER_DEFAULT)
            scale_window = get_scalar_param(fp16_dict,
                                            FP16_LOSS_SCALE_WINDOW,
                                            FP16_LOSS_SCALE_WINDOW_DEFAULT)
            delayed_shift = get_scalar_param(fp16_dict,
                                             FP16_HYSTERESIS,
                                             FP16_HYSTERESIS_DEFAULT)
            min_loss_scale = get_scalar_param(fp16_dict,
                                              FP16_MIN_LOSS_SCALE,
                                              FP16_MIN_LOSS_SCALE_DEFAULT)
            loss_scale_args = {
                "INITIAL_LOSS_SCALE": 2 ** init_scale,
                "SCALE_WINDOW": scale_window,
                "DELAYED_SHIFT": delayed_shift,
                "MIN_LOSS_SCALE": min_loss_scale,
            }
    return loss_scale_args


def get_gradient_accumulation_steps(param_dict):
    return get_scalar_param(param_dict,
                            GRADIENT_ACCUMULATION_STEPS,
                            GRADIENT_ACCUMULATION_STEPS_DEFAULT)


def get_sparse_gradients_enabled(param_dict):
    return get_scalar_param(param_dict, SPARSE_GRADIENTS, SPARSE_GRADIENTS_DEFAULT)


def get_sequence_parallel_enabled(param_dict):
    sub = param_dict.get(SEQUENCE_PARALLEL, {})
    return get_scalar_param(sub, SEQUENCE_PARALLEL_ENABLED,
                            SEQUENCE_PARALLEL_ENABLED_DEFAULT)


def get_sequence_parallel_size(param_dict):
    sub = param_dict.get(SEQUENCE_PARALLEL, {})
    return get_scalar_param(sub, SEQUENCE_PARALLEL_SIZE,
                            SEQUENCE_PARALLEL_SIZE_DEFAULT)


def get_zero_allow_untested_optimizer(param_dict):
    return get_scalar_param(param_dict,
                            ZERO_ALLOW_UNTESTED_OPTIMIZER,
                            ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)


def get_gradient_clipping(param_dict):
    return get_scalar_param(param_dict, GRADIENT_CLIPPING, GRADIENT_CLIPPING_DEFAULT)


def get_sparse_attention(param_dict):
    if SPARSE_ATTENTION in param_dict.keys():
        sparsity = param_dict[SPARSE_ATTENTION]
        mode = get_sparse_attention_mode(sparsity)
        if mode == SPARSE_DENSE_MODE:
            return get_sparse_dense_config(sparsity)
        elif mode == SPARSE_FIXED_MODE:
            return get_sparse_fixed_config(sparsity)
        elif mode == SPARSE_VARIABLE_MODE:
            return get_sparse_variable_config(sparsity)
        elif mode == SPARSE_BIGBIRD_MODE:
            return get_sparse_bigbird_config(sparsity)
        elif mode == SPARSE_BSLONGFORMER_MODE:
            return get_sparse_bslongformer_config(sparsity)
        else:
            raise NotImplementedError(
                "Given sparsity mode, {}, has not been implemented yet!".format(mode))
    return None


def get_sparse_dense_config(sparsity):
    block = get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT)
    return {SPARSE_MODE: SPARSE_DENSE_MODE, SPARSE_BLOCK: block}


def get_sparse_fixed_config(sparsity):
    block = get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT)
    different_layout_per_head = get_scalar_param(
        sparsity,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT)
    num_local_blocks = get_scalar_param(sparsity,
                                        SPARSE_NUM_LOCAL_BLOCKS,
                                        SPARSE_NUM_LOCAL_BLOCKS_DEFAULT)
    num_global_blocks = get_scalar_param(sparsity,
                                         SPARSE_NUM_GLOBAL_BLOCKS,
                                         SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT)
    attention = get_scalar_param(sparsity,
                                 SPARSE_ATTENTION_TYPE,
                                 SPARSE_ATTENTION_TYPE_DEFAULT)
    horizontal_global_attention = get_scalar_param(
        sparsity,
        SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
        SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT)
    num_different_global_patterns = get_scalar_param(
        sparsity,
        SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
        SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT)
    return {
        SPARSE_MODE: SPARSE_FIXED_MODE,
        SPARSE_BLOCK: block,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD: different_layout_per_head,
        SPARSE_NUM_LOCAL_BLOCKS: num_local_blocks,
        SPARSE_NUM_GLOBAL_BLOCKS: num_global_blocks,
        SPARSE_ATTENTION_TYPE: attention,
        SPARSE_HORIZONTAL_GLOBAL_ATTENTION: horizontal_global_attention,
        SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS: num_different_global_patterns,
    }


def get_sparse_variable_config(sparsity):
    block = get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT)
    different_layout_per_head = get_scalar_param(
        sparsity,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT)
    num_random_blocks = get_scalar_param(sparsity,
                                         SPARSE_NUM_RANDOM_BLOCKS,
                                         SPARSE_NUM_RANDOM_BLOCKS_DEFAULT)
    local_window_blocks = get_scalar_param(sparsity,
                                           SPARSE_LOCAL_WINDOW_BLOCKS,
                                           SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT)
    global_block_indices = get_scalar_param(sparsity,
                                            SPARSE_GLOBAL_BLOCK_INDICES,
                                            SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT)
    global_block_end_indices = get_scalar_param(
        sparsity,
        SPARSE_GLOBAL_BLOCK_END_INDICES,
        SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT)
    attention = get_scalar_param(sparsity,
                                 SPARSE_ATTENTION_TYPE,
                                 SPARSE_ATTENTION_TYPE_DEFAULT)
    horizontal_global_attention = get_scalar_param(
        sparsity,
        SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
        SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT)
    return {
        SPARSE_MODE: SPARSE_VARIABLE_MODE,
        SPARSE_BLOCK: block,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD: different_layout_per_head,
        SPARSE_NUM_RANDOM_BLOCKS: num_random_blocks,
        SPARSE_LOCAL_WINDOW_BLOCKS: local_window_blocks,
        SPARSE_GLOBAL_BLOCK_INDICES: global_block_indices,
        SPARSE_GLOBAL_BLOCK_END_INDICES: global_block_end_indices,
        SPARSE_ATTENTION_TYPE: attention,
        SPARSE_HORIZONTAL_GLOBAL_ATTENTION: horizontal_global_attention,
    }


def get_sparse_bigbird_config(sparsity):
    block = get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT)
    different_layout_per_head = get_scalar_param(
        sparsity,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT)
    num_random_blocks = get_scalar_param(sparsity,
                                         SPARSE_NUM_RANDOM_BLOCKS,
                                         SPARSE_NUM_RANDOM_BLOCKS_DEFAULT)
    num_sliding_window_blocks = get_scalar_param(
        sparsity,
        SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
        SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT)
    num_global_blocks = get_scalar_param(sparsity,
                                         SPARSE_NUM_GLOBAL_BLOCKS,
                                         SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT)
    return {
        SPARSE_MODE: SPARSE_BIGBIRD_MODE,
        SPARSE_BLOCK: block,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD: different_layout_per_head,
        SPARSE_NUM_RANDOM_BLOCKS: num_random_blocks,
        SPARSE_NUM_SLIDING_WINDOW_BLOCKS: num_sliding_window_blocks,
        SPARSE_NUM_GLOBAL_BLOCKS: num_global_blocks,
    }


def get_sparse_bslongformer_config(sparsity):
    block = get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT)
    different_layout_per_head = get_scalar_param(
        sparsity,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT)
    num_sliding_window_blocks = get_scalar_param(
        sparsity,
        SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
        SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT)
    global_block_indices = get_scalar_param(sparsity,
                                            SPARSE_GLOBAL_BLOCK_INDICES,
                                            SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT)
    global_block_end_indices = get_scalar_param(
        sparsity,
        SPARSE_GLOBAL_BLOCK_END_INDICES,
        SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT)
    return {
        SPARSE_MODE: SPARSE_BSLONGFORMER_MODE,
        SPARSE_BLOCK: block,
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD: different_layout_per_head,
        SPARSE_NUM_SLIDING_WINDOW_BLOCKS: num_sliding_window_blocks,
        SPARSE_GLOBAL_BLOCK_INDICES: global_block_indices,
        SPARSE_GLOBAL_BLOCK_END_INDICES: global_block_end_indices,
    }


def get_sparse_attention_mode(param_dict):
    return get_scalar_param(param_dict, SPARSE_MODE, SPARSE_MODE_DEFAULT)


def get_sparse_attention_type(param_dict):
    return get_scalar_param(param_dict,
                            SPARSE_ATTENTION_TYPE,
                            SPARSE_ATTENTION_TYPE_DEFAULT)


def get_pipeline_config(param_dict):
    """Parse the pipeline engine block (reference config.py:363-375)."""
    default_pipeline = {
        "stages": "auto",
        "partition": "best",
        "seed_layers": False,
        "activation_checkpoint_interval": 0,
    }
    config = default_pipeline
    for key, val in param_dict.get("pipeline", {}).items():
        config[key] = val
    return config


def get_optimizer_name(param_dict):
    if OPTIMIZER in param_dict.keys() and TYPE in param_dict[OPTIMIZER].keys():
        return param_dict[OPTIMIZER][TYPE]
    return OPTIMIZER_TYPE_DEFAULT


def get_optimizer_params(param_dict):
    if get_optimizer_name(param_dict) is not None and \
            OPTIMIZER_PARAMS in param_dict[OPTIMIZER].keys():
        return param_dict[OPTIMIZER][OPTIMIZER_PARAMS]
    return None


def get_optimizer_gradient_clipping(param_dict):
    optimizer_params = get_optimizer_params(param_dict)
    if optimizer_params is not None and MAX_GRAD_NORM in optimizer_params.keys():
        return optimizer_params[MAX_GRAD_NORM]
    return None


def get_optimizer_legacy_fusion(param_dict):
    if OPTIMIZER in param_dict.keys() and LEGACY_FUSION in param_dict[OPTIMIZER].keys():
        return param_dict[OPTIMIZER][LEGACY_FUSION]
    return LEGACY_FUSION_DEFAULT


def get_scheduler_name(param_dict):
    if SCHEDULER in param_dict.keys() and TYPE in param_dict[SCHEDULER].keys():
        return param_dict[SCHEDULER][TYPE]
    return SCHEDULER_TYPE_DEFAULT


def get_scheduler_params(param_dict):
    if get_scheduler_name(param_dict) is not None and \
            SCHEDULER_PARAMS in param_dict[SCHEDULER].keys():
        return param_dict[SCHEDULER][SCHEDULER_PARAMS]
    return None


def get_train_batch_size(param_dict):
    return get_scalar_param(param_dict, TRAIN_BATCH_SIZE, TRAIN_BATCH_SIZE_DEFAULT)


def get_train_micro_batch_size_per_gpu(param_dict):
    return get_scalar_param(param_dict,
                            TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                            TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)


def get_wall_clock_breakdown(param_dict):
    return get_scalar_param(param_dict,
                            WALL_CLOCK_BREAKDOWN,
                            WALL_CLOCK_BREAKDOWN_DEFAULT)


def get_memory_breakdown(param_dict):
    return get_scalar_param(param_dict, MEMORY_BREAKDOWN, MEMORY_BREAKDOWN_DEFAULT)


def get_tensorboard_enabled(param_dict):
    if TENSORBOARD in param_dict.keys():
        return get_scalar_param(param_dict[TENSORBOARD],
                                TENSORBOARD_ENABLED,
                                TENSORBOARD_ENABLED_DEFAULT)
    return False


def get_tensorboard_output_path(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[TENSORBOARD],
                                TENSORBOARD_OUTPUT_PATH,
                                TENSORBOARD_OUTPUT_PATH_DEFAULT)
    return TENSORBOARD_OUTPUT_PATH_DEFAULT


def get_tensorboard_job_name(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[TENSORBOARD],
                                TENSORBOARD_JOB_NAME,
                                TENSORBOARD_JOB_NAME_DEFAULT)
    return TENSORBOARD_JOB_NAME_DEFAULT


def get_steps_per_print(param_dict):
    return get_scalar_param(param_dict, STEPS_PER_PRINT, STEPS_PER_PRINT_DEFAULT)


def get_disable_allgather(param_dict):
    return get_scalar_param(param_dict, DISABLE_ALLGATHER, DISABLE_ALLGATHER_DEFAULT)


def get_dump_state(param_dict):
    return get_scalar_param(param_dict, DUMP_STATE, DUMP_STATE_DEFAULT)


def get_gradient_predivide_factor(param_dict):
    return get_scalar_param(param_dict,
                            GRADIENT_PREDIVIDE_FACTOR,
                            GRADIENT_PREDIVIDE_FACTOR_DEFAULT)


def get_allreduce_always_fp32(param_dict):
    return get_scalar_param(param_dict, FP32_ALLREDUCE, FP32_ALLREDUCE_DEFAULT)


def get_prescale_gradients(param_dict):
    return get_scalar_param(param_dict, PRESCALE_GRADIENTS, PRESCALE_GRADIENTS_DEFAULT)


def get_pld_enabled(param_dict):
    if PROGRESSIVE_LAYER_DROP in param_dict.keys():
        return get_scalar_param(param_dict[PROGRESSIVE_LAYER_DROP],
                                PLD_ENABLED,
                                PLD_ENABLED_DEFAULT)
    return False


def get_pld_params(param_dict):
    if get_pld_enabled(param_dict):
        pld_params = dict(param_dict[PROGRESSIVE_LAYER_DROP])
        pld_params.pop(PLD_ENABLED, None)
        return pld_params
    return False


def get_checkpoint_params(param_dict):
    return param_dict.get(CHECKPOINT, {})


def get_checkpoint_tag_validation_mode(checkpoint_params):
    tag_validation_mode = checkpoint_params.get(CHECKPOINT_TAG_VALIDATION,
                                                CHECKPOINT_TAG_VALIDATION_DEFAULT)
    tag_validation_mode = tag_validation_mode.upper()
    if tag_validation_mode in CHECKPOINT_TAG_VALIDATION_MODES:
        return tag_validation_mode
    raise ValueError(
        "Checkpoint config contains invalid tag_validation "
        "value of {}, expecting one of {}".format(tag_validation_mode,
                                                  CHECKPOINT_TAG_VALIDATION_MODES))


def _default_world_size(mpu=None):
    """Data-parallel world size: mpu if given, else total JAX device count."""
    if mpu is not None:
        return mpu.get_data_parallel_world_size()
    try:
        import jax
        return jax.device_count()
    except Exception:
        return 1


def _default_global_rank():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


class DeepSpeedConfig(object):
    def __init__(self, json_file, mpu=None, param_dict=None, world_size=None):
        super(DeepSpeedConfig, self).__init__()

        if param_dict is None:
            with open(json_file, "r") as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        else:
            self._param_dict = param_dict

        self.global_rank = _default_global_rank()
        self.world_size = world_size if world_size is not None else _default_world_size(mpu)

        # If elastic-mode enabled, compute batch params and update _param_dict
        # (reference config.py:538-589).
        self.elasticity_enabled = elasticity_enabled(self._param_dict)
        if self.elasticity_enabled:
            logger.info("DeepSpeed elasticity support enabled")
            final_batch_size, valid_gpus, micro_batch_size = compute_elastic_config(
                ds_config=self._param_dict,
                target_deepspeed_version=__version__,
                world_size=self.world_size)

            elastic_dict = self._param_dict[ELASTICITY]
            ensure_immutable_elastic_config(runtime_elastic_config_dict=elastic_dict)

            ignore_non_elastic_batch_info = elastic_dict.get(
                IGNORE_NON_ELASTIC_BATCH_INFO,
                IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

            if not ignore_non_elastic_batch_info:
                batch_params = [
                    TRAIN_BATCH_SIZE,
                    TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                    GRADIENT_ACCUMULATION_STEPS,
                ]
                if any(t in self._param_dict for t in batch_params):
                    raise ElasticityConfigError(
                        "One or more batch related parameters were found in your "
                        "ds_config ({}, {}, and/or {}). These parameters *will "
                        "not be used* since elastic training is enabled, which "
                        "takes control of these parameters. If you want to "
                        "suppress this error (the parameters will be silently "
                        "ignored) please set {}':true in your elasticity "
                        "config.".format(TRAIN_BATCH_SIZE,
                                         TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                                         GRADIENT_ACCUMULATION_STEPS,
                                         IGNORE_NON_ELASTIC_BATCH_INFO))

            gradient_accu_steps = final_batch_size // (micro_batch_size *
                                                       self.world_size)
            logger.info("[Elasticity] valid chip counts: {}".format(valid_gpus))

            self._param_dict[TRAIN_BATCH_SIZE] = final_batch_size
            self._param_dict[TRAIN_MICRO_BATCH_SIZE_PER_GPU] = micro_batch_size
            self._param_dict[GRADIENT_ACCUMULATION_STEPS] = gradient_accu_steps

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    def _initialize_params(self, param_dict):
        self.train_batch_size = get_train_batch_size(param_dict)
        self.train_micro_batch_size_per_gpu = get_train_micro_batch_size_per_gpu(
            param_dict)
        self.gradient_accumulation_steps = get_gradient_accumulation_steps(param_dict)
        self.steps_per_print = get_steps_per_print(param_dict)
        self.dump_state = get_dump_state(param_dict)

        self.disable_allgather = get_disable_allgather(param_dict)
        self.allreduce_always_fp32 = get_allreduce_always_fp32(param_dict)
        self.prescale_gradients = get_prescale_gradients(param_dict)
        self.gradient_predivide_factor = get_gradient_predivide_factor(param_dict)
        self.sparse_gradients_enabled = get_sparse_gradients_enabled(param_dict)
        self.sequence_parallel_enabled = get_sequence_parallel_enabled(param_dict)
        self.sequence_parallel_size = get_sequence_parallel_size(param_dict)

        self.zero_config = DeepSpeedZeroConfig(param_dict)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.activation_checkpointing_config = \
            DeepSpeedActivationCheckpointingConfig(param_dict)

        self.gradient_clipping = get_gradient_clipping(param_dict)
        self.fp16_enabled = get_fp16_enabled(param_dict)
        self.bfloat16_enabled = get_bfloat16_enabled(param_dict)
        self.amp_enabled = get_amp_enabled(param_dict)
        self.amp_params = get_amp_params(param_dict)
        self.loss_scale = get_loss_scale(param_dict)
        self.initial_dynamic_scale = get_initial_dynamic_scale(param_dict)
        self.dynamic_loss_scale_args = get_dynamic_loss_scale_args(param_dict)

        self.optimizer_name = get_optimizer_name(param_dict)
        if self.optimizer_name is not None and \
                self.optimizer_name.lower() in DEEPSPEED_OPTIMIZERS:
            self.optimizer_name = self.optimizer_name.lower()

        self.optimizer_params = get_optimizer_params(param_dict)
        self.optimizer_legacy_fusion = get_optimizer_legacy_fusion(param_dict)

        self.zero_allow_untested_optimizer = get_zero_allow_untested_optimizer(
            param_dict)

        self.scheduler_name = get_scheduler_name(param_dict)
        self.scheduler_params = get_scheduler_params(param_dict)

        self.wall_clock_breakdown = get_wall_clock_breakdown(param_dict)
        self.flops_profiler_config = DeepSpeedFlopsProfilerConfig(param_dict)
        self.memory_breakdown = get_memory_breakdown(param_dict)
        self.tensorboard_enabled = get_tensorboard_enabled(param_dict)
        self.tensorboard_output_path = get_tensorboard_output_path(param_dict)
        self.tensorboard_job_name = get_tensorboard_job_name(param_dict)

        self.sparse_attention = get_sparse_attention(param_dict)
        self.pipeline = get_pipeline_config(param_dict)

        self.pld_enabled = get_pld_enabled(param_dict)
        self.pld_params = get_pld_params(param_dict)

        checkpoint_params = get_checkpoint_params(param_dict)
        validation_mode = get_checkpoint_tag_validation_mode(checkpoint_params)
        self.checkpoint_tag_validation_enabled = \
            validation_mode != ValidationMode.IGNORE
        self.checkpoint_tag_validation_fail = validation_mode == ValidationMode.FAIL

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        assert train_batch > 0, \
            "Train batch size: {} has to be greater than 0".format(train_batch)
        assert micro_batch > 0, \
            "Micro batch size per gpu: {} has to be greater than 0".format(micro_batch)
        assert grad_acc > 0, \
            "Gradient accumulation steps: {} has to be greater than 0".format(grad_acc)
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            "Check batch related parameters. train_batch_size is not equal to "
            "micro_batch_per_gpu * gradient_acc_step * world_size "
            "{} != {} * {} * {}".format(train_batch,
                                        micro_batch,
                                        grad_acc,
                                        self.world_size))

    def _set_batch_related_parameters(self):
        """Batch triangle completion (reference config.py:675-721)."""
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        if train_batch is not None and micro_batch is not None and \
                grad_acc is not None:
            return
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= self.world_size
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // self.world_size
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * self.world_size
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        else:
            assert False, \
                "Either train_batch_size or micro_batch_per_gpu needs to be provided"

    def _configure_train_batch_size(self):
        self._set_batch_related_parameters()
        self._batch_assertion()

    def _do_sanity_check(self):
        self._do_error_check()
        self._do_warning_check()

    def print(self, name):
        logger.info("{}:".format(name))
        for arg in sorted(vars(self)):
            if arg != "_param_dict":
                dots = "." * (29 - len(arg))
                logger.info("  {} {} {}".format(arg, dots, getattr(self, arg)))
        logger.info("  json = {}".format(
            json.dumps(self._param_dict,
                       sort_keys=True,
                       indent=4,
                       separators=(",", ":"))))

    def _do_error_check(self):
        assert self.train_micro_batch_size_per_gpu, \
            "DeepSpeedConfig: {} is not defined".format(TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        assert self.gradient_accumulation_steps, \
            "DeepSpeedConfig: {} is not defined".format(GRADIENT_ACCUMULATION_STEPS)

        if self.zero_enabled:
            # TPU delta: bf16 satisfies the mixed-precision requirement
            # (reference requires fp16: config.py:750-752).
            assert self.fp16_enabled or self.bfloat16_enabled, \
                "DeepSpeedConfig: ZeRO is only supported if fp16 or bf16 is enabled"
            assert self.zero_optimization_stage <= MAX_STAGE_ZERO_OPTIMIZATION, \
                "DeepSpeedConfig: Maximum supported ZeRO stage is {}".format(
                    MAX_STAGE_ZERO_OPTIMIZATION)
            if self.zero_config.cpu_offload is True:
                assert self.zero_optimization_stage == ZERO_OPTIMIZATION_GRADIENTS, \
                    "DeepSpeedConfig: cpu-offload supported ZeRO stage is {}".format(
                        ZERO_OPTIMIZATION_GRADIENTS)

    def _do_warning_check(self):
        fp16_enabled = self.fp16_enabled or self.zero_enabled

        vocabulary_size = self._param_dict.get(VOCABULARY_SIZE,
                                               VOCABULARY_SIZE_DEFAULT)
        if vocabulary_size and vocabulary_size % TENSOR_CORE_ALIGN_SIZE != 0:
            logger.warning(
                "DeepSpeedConfig: vocabulary size {} is not aligned to {}, may "
                "impact MXU utilization.".format(vocabulary_size,
                                                 TENSOR_CORE_ALIGN_SIZE))

        if self.optimizer_params is not None and \
                MAX_GRAD_NORM in self.optimizer_params.keys() and \
                self.optimizer_params[MAX_GRAD_NORM] > 0:
            if fp16_enabled:
                if self.global_rank == 0:
                    logger.warning(
                        "DeepSpeedConfig: In FP16 mode, DeepSpeed will pass "
                        "{}:{} to FP16 wrapper".format(
                            MAX_GRAD_NORM, self.optimizer_params[MAX_GRAD_NORM]))
            else:
                if self.global_rank == 0:
                    logger.warning(
                        "DeepSpeedConfig: In FP32 mode, DeepSpeed does not "
                        "permit MAX_GRAD_NORM ({}) > 0, setting to zero".format(
                            self.optimizer_params[MAX_GRAD_NORM]))
                self.optimizer_params[MAX_GRAD_NORM] = 0.0
