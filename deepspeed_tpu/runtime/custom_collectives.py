"""Compressed collectives for 1-bit Adam.

The reference implements an error-compensated 1-bit allreduce with raw MPI +
cupy (deepspeed/runtime/custom_collectives.py:10-155: my_igather/gather/
allgather of sign-packed bits) because NCCL lacked non-blocking gathers. On
TPU the same exchange maps onto two XLA collectives over the data-parallel
mesh axis: an ``all_to_all`` (each worker scatters its sign-packed chunks —
the reference's igather phase 1) and an ``all_gather`` (the server-side
re-broadcast — phase 2), both riding ICI. Signs are genuinely bit-packed into
uint8 words, so the wire volume is n/8 bytes + one fp32 scale per phase —
the same 1-bit-per-element compression the reference achieves with
cupy.packbits (onebit_adam.py:98-102).

Everything here is pure-functional and shard_map-compatible; use inside
``shard_map(..., mesh, in_specs=..., check_rep=False)`` over the 'data' axis.
"""

import jax
import jax.numpy as jnp
import numpy as np


def pack_signs(x):
    """Pack the sign bits of ``x`` (>=0 → 1, <0 → 0) into uint8 words.

    x: [n] float, n % 8 == 0 → uint8 [n/8]. Big-endian within each byte,
    matching numpy/cupy packbits so tests can cross-check against numpy.
    """
    bits = (x >= 0).astype(jnp.uint8).reshape(-1, 8)
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], dtype=jnp.uint8)
    return jnp.sum(bits * weights[None, :], axis=1, dtype=jnp.uint8)


def unpack_signs(packed):
    """uint8 [m] → float32 [m*8] of ±1 values."""
    shifts = jnp.asarray([7, 6, 5, 4, 3, 2, 1, 0], dtype=jnp.uint8)
    bits = (packed[:, None] >> shifts[None, :]) & 1
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def corrected_size(n, world_size):
    """Padded element count: the invariant is n % world_size == 0 and
    (n // world_size) % 8 == 0, i.e. n a multiple of 8*world_size.

    The reference rounds up to world_size*lcm(world_size,8)
    (onebit_adam.py:86, :295-299) — up to world_size/gcd(world_size,8)×
    over-padding, which biases the quantization scale (norm/sqrt(n) over the
    zero padding) low for small tensors at large world sizes. We pad to the
    minimal sufficient block instead.
    """
    block = world_size * 8
    if n % block:
        n += block - (n % block)
    return n


def compressed_allreduce(buffer, worker_error, server_error, axis_name):
    """Error-compensated 1-bit allreduce (reference Compressed_Allreduce,
    onebit_adam.py:104-233), as a pure function over a mesh axis.

    Args:
      buffer: [n] float32, this worker's value (n already padded to
        ``corrected_size``; the optimizer pads).
      worker_error: [n] float32 error-feedback state (phase 1).
      server_error: [n / W] float32 error-feedback state for this worker's
        server chunk (phase 2).
      axis_name: mesh axis to reduce over.

    Returns (averaged [n], new_worker_error, new_server_error). The result is
    identical on every worker (it is built from all-gathered server chunks).
    """
    w = jax.lax.psum(1, axis_name)
    n = buffer.shape[0]
    chunk = n // w

    # --- worker-side compression (with error feedback)
    buffer = buffer + worker_error
    worker_scale = jnp.linalg.norm(buffer) / np.sqrt(n)
    sign = jnp.where(buffer >= 0, 1.0, -1.0)
    new_worker_error = buffer - worker_scale * sign

    # --- phase 1: scatter sign chunks so worker r holds everyone's chunk r
    packed = pack_signs(sign).reshape(w, chunk // 8)
    recv_signs, all_scales = gather_tpu(axis_name, packed, worker_scale)

    # --- server-side average + re-compression for my chunk
    unpacked = jax.vmap(unpack_signs)(recv_signs)                 # [w, chunk]
    server_m = jnp.mean(unpacked * all_scales[:, None], axis=0)
    server_m = server_m + server_error
    server_scale = jnp.linalg.norm(server_m) / np.sqrt(chunk)
    server_sign = jnp.where(server_m >= 0, 1.0, -1.0)
    new_server_error = server_m - server_scale * server_sign

    # --- phase 2: all_gather compressed server chunks
    server_packed = pack_signs(server_sign)                       # [chunk/8]
    gathered, gathered_scales = allgather_tpu(axis_name, server_packed,
                                              server_scale)
    out = (jax.vmap(unpack_signs)(gathered) *
           gathered_scales[:, None]).reshape(-1)
    return out, new_worker_error, new_server_error


def quantize_error_feedback(buffer, error):
    """Single-party 1-bit quantization with error feedback — the degenerate
    (identical-workers) form of compressed_allreduce.

    Under single-controller GSPMD the gradients reaching the optimizer are
    already globally averaged, so every worker's momentum is identical and
    phase 1 of the exchange is mathematically the identity; what remains is
    the server-side quantize/compensate. Used by OnebitAdam's jit path; the
    full two-phase collective above is for shard_map pipelines that keep
    per-worker local gradients.
    """
    compensated = buffer + error
    scale = jnp.linalg.norm(compensated) / np.sqrt(compensated.size)
    sign = jnp.where(compensated >= 0, 1.0, -1.0)
    new_error = compensated - scale * sign
    return scale * sign, new_error


# Reference-compatible collective phases (custom_collectives.py:10-155:
# gather_cuda/gather_host scatter packed sign chunks + scales so rank r
# holds everyone's chunk r; allgather_cuda/allgather_host rebroadcast the
# re-compressed server chunks). The reference needs four variants because
# raw-MPI igather requires host buffers while cupy sometimes allows device
# pointers; on TPU ONE implementation per phase serves both — an XLA
# collective over the mesh axis, usable inside shard_map — and they are
# the actual building blocks of compressed_allreduce above.

def gather_tpu(axis_name, sign_list_packed, worker_scale):
    """Phase-1 exchange: each worker offers [w, chunk/8] packed sign
    chunks; returns (this worker's received [w, chunk/8] — chunk r from
    every peer — and everyone's scales [w])."""
    recv_signs = jax.lax.all_to_all(sign_list_packed, axis_name,
                                    split_axis=0, concat_axis=0,
                                    tiled=False)
    all_scales = jax.lax.all_gather(worker_scale, axis_name)
    return recv_signs, all_scales


def allgather_tpu(axis_name, server_sign_packed, server_scale):
    """Phase-2 exchange: rebroadcast each worker's re-compressed server
    chunk [chunk/8] + scale; returns ([w, chunk/8], [w])."""
    gathered = jax.lax.all_gather(server_sign_packed, axis_name)
    gathered_scales = jax.lax.all_gather(server_scale, axis_name)
    return gathered, gathered_scales


gather_cuda = gather_host = gather_tpu
allgather_cuda = allgather_host = allgather_tpu
