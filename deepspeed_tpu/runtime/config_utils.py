"""Config helpers: scalar getters + duplicate-key-rejecting JSON object hook.

Mirrors reference runtime/config_utils.py (27 LoC): ``dict_raise_error_on_duplicate_keys``
is the object_pairs_hook passed to json.load so malformed configs fail loudly.
"""


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys while building a dict from JSON pairs."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(keys))
    return d
