"""DeepSpeedEngine — the core TPU training engine.

TPU-native re-design of reference runtime/engine.py:95 (DeepSpeedEngine, 1561
LoC). The public surface is preserved — ``forward`` / ``backward`` / ``step``
driven by an unchanged ds_config.json, plus checkpoint save/load — but the
execution model is JAX-first:

- ``forward(*inputs)`` runs ONE jitted ``value_and_grad`` program (forward and
  backward fused by XLA) and caches the gradients; it returns the loss, so the
  classic ``loss = engine(x); engine.backward(loss); engine.step()`` loop
  works unchanged while doing no redundant compute. The reference's per-param
  backward hooks / IPG bucket machinery (stage2.py:583-1060) vanish: gradient
  reduction is a GSPMD sharding constraint and XLA overlaps it with compute.
- ZeRO stages are sharding policies over the 'data' mesh axis
  (parallel/mesh.py:zero_shardings): stage 1 shards optimizer state, stage 2
  reduce-scatters gradients (psum_scatter), stage 3 shards parameters. The
  optimizer update runs on each rank's shard; params re-materialize via XLA
  all-gather exactly like stage2.py:1444-1477's sharded allgather, but
  compiler-scheduled.
- Mixed precision: fp32 master params always; compute casts to bf16 (TPU
  default) or fp16 with full DynamicLossScaler semantics (overflow-skip,
  scale-window bookkeeping — reference fp16/fused_optimizer.py).
- ``train_batch(batch)`` is the fused fast path: fwd+bwd+update in one XLA
  program with donated buffers (benchmarks use this).
"""

import glob
import hashlib
import json
import os
import pickle
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.runtime import lr_schedules
from deepspeed_tpu.runtime.config import (
    ADAM_OPTIMIZER,
    ADAMW_OPTIMIZER,
    DEEPSPEED_OPTIMIZERS,
    LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER,
    DeepSpeedConfig,
)
from deepspeed_tpu.runtime.constants import ROUTE_TRAIN
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.runtime.fp16.loss_scaler import CreateLossScaler
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.runtime.utils import (
    clip_grad_norm_,
    ensure_directory_exists,
    has_overflow,
    jit_has_overflow,
)
from deepspeed_tpu.runtime.utils import global_norm as utils_global_norm
from deepspeed_tpu.telemetry import (MetricsRegistry, ProgramRegistry,
                                     TensorBoardScalarWriter)
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer


class _StreamedGrads:
    """Marker for gradients that already live in the offload host buffer
    (streamed there by io_callback DURING the fused backward); carries the
    device-computed per-leaf squared norms (global-norm clipping + fp16
    overflow check) and the callback completion token — the host buffer
    MUST NOT be read before the token is fetched (sqnorms alone does not
    depend on the callbacks, so fetching it proves nothing)."""

    def __init__(self, sqnorms, token):
        self.sqnorms = sqnorms
        self.token = token


MEMORY_OPT_ALLREDUCE_SIZE = 500000000

# Debug cross-check toggle (reference stage2.py:23-25 pg_correctness_test,
# which forces deterministic fp32 allreduce so partitioned gradients can be
# compared against unpartitioned ones). TPU analog: with the flag on, every
# training fwd+bwd ALSO runs an unconstrained program (no ZeRO gradient
# sharding constraints, fully replicated batch) and asserts the sharded
# path produced the same gradients — catching partitioner/constraint bugs
# at the step they occur. Debug-only: doubles compute per step.
pg_correctness_test = False

SUMMARY_WRITER_DIR_NAME = "JobId"


def split_half_float_double_csr(tensors):
    """Bucket tensors by dtype with CSR tensors in their own bucket
    (reference engine.py:54-66, which keys off torch tensor type strings).
    TPU form: (dtype name, bucket) pairs over jnp dtypes + CSRTensor."""
    from deepspeed_tpu.runtime.csr_tensor import CSRTensor

    order = [jnp.bfloat16.dtype.name, jnp.float16.dtype.name,
             jnp.float32.dtype.name, jnp.float64.dtype.name,
             CSRTensor.type()]
    groups = {}
    for t in tensors:  # single pass
        key = CSRTensor.type() if isinstance(t, CSRTensor) \
            else jnp.asarray(t).dtype.name
        groups.setdefault(key if key in order else "other", []).append(t)
    return [(dtype, groups[dtype]) for dtype in order + ["other"]
            if dtype in groups]


class DeepSpeedEngine(object):
    """The TPU DeepSpeed engine. Wraps a flax module (or any object with
    ``init``/``apply``) and executes its training loop via jitted XLA programs
    over a device mesh."""

    def __init__(self,
                 args,
                 model,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu=None,
                 dist_init_required=None,
                 collate_fn=None,
                 config_params=None,
                 dont_change_device=False,
                 mesh=None,
                 seed=1234):
        self.client_optimizer = optimizer
        self.client_model_parameters = model_parameters
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn
        self.mpu = mpu
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.gradient_average = True
        # API-parity flag (reference engine.py:369-372 reads it to skip the
        # dense allreduce). On the TPU jit path gradient reduction is a GSPMD
        # sharding decision made at trace time, so this flag is informational:
        # OnebitAdam flips it at the freeze boundary so user scripts that
        # consult it (as with the reference) observe the same transition.
        self.enable_backward_allreduce = True
        self.warn_unscaled_loss = True
        self.progressive_layer_drop = None
        self.dist_backend = "xla-ici"

        # Device mesh: the TPU-native replacement for process groups.
        self.mesh = mesh if mesh is not None else mesh_lib.build_mesh()
        self.dp_world_size = self._config_world_size()
        self.mp_world_size = mesh_lib.mp_size(self.mesh)
        self.world_size = self.dp_world_size
        self.global_rank = 0
        self.local_rank = getattr(args, "local_rank", 0) if args else 0

        # Sequence parallelism reshapes the mesh (dp x sp), which feeds the
        # batch triangle (train = micro * gas * dp) — peek at the raw config
        # BEFORE the full parse validates batch sizes.
        sp_enabled, sp_size = self._peek_sequence_parallel(args, config_params)
        if sp_enabled:
            self._setup_sequence_parallel_mesh(mesh, sp_size)

        self._config = self._configure_with_arguments(args, config_params)
        self._do_args_sanity_check(args)

        self.module = model
        self.training = True

        # RNG: pure threefry keys replace the reference's CUDA RNG tracker.
        self._rng = jax.random.PRNGKey(seed)

        # Precision policy (fp32 master params always).
        if self.amp_enabled():
            # The reference hands `amp: {...}` to apex.amp.initialize
            # (reference engine.py:569-575). The TPU-native cast policy
            # that matches apex O1/O2 semantics — mixed-precision compute
            # against fp32 master weights, no loss scaling required — is
            # bf16 compute, which this engine already implements; amp maps
            # onto it. Like the reference, amp is mutually exclusive with
            # the explicit fp16/bf16 blocks.
            if self.fp16_enabled() or self.bfloat16_enabled():
                raise ValueError(
                    "amp is mutually exclusive with the fp16/bf16 config "
                    "blocks (reference semantics); enable exactly one")
            opt_level = dict(self.amp_params() or {}).get("opt_level", "O1")
            if opt_level not in ("O0", "O1", "O2", "O3"):
                raise ValueError("unknown amp opt_level {!r}".format(opt_level))
            log_dist("amp enabled (opt_level {}): mapped to the bf16 "
                     "mixed-precision policy (bf16 compute, fp32 master "
                     "params)".format(opt_level), ranks=[0])
            self.compute_dtype = (jnp.float32 if opt_level == "O0"
                                  else jnp.bfloat16)
        elif self.fp16_enabled():
            self.compute_dtype = jnp.float16
        elif self.bfloat16_enabled():
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32

        # Telemetry registry (telemetry/): the wall_clock_breakdown
        # timers observe their phase durations into it as timer_seconds
        # histograms, the throughput timer exposes a live
        # samples_per_sec gauge, and the step/sample/lr trackers below
        # read the engine's own state at scrape time. Exporters
        # (Prometheus text, the TensorBoard scalar writer behind the
        # tensorboard_* config keys) read the same registry.
        self.telemetry = MetricsRegistry(engine="training")
        self.timers = SynchronizedWallClockTimer(registry=self.telemetry)
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu(),
            num_workers=self.dp_world_size,
            steps_per_output=self.steps_per_print(),
            monitor_memory=False,
            registry=self.telemetry)
        self.telemetry.gauge("global_steps").set_fn(
            lambda: self.global_steps)
        self.telemetry.gauge("global_samples").set_fn(
            lambda: self.global_samples)
        self.telemetry.gauge("skipped_steps").set_fn(
            lambda: self.skipped_steps)
        self.telemetry.gauge("lr").set_fn(
            lambda: (self.get_lr() if self.optimizer else [0.0])[0])
        # Perf X-ray (telemetry/xray.py): train_batch's fused path
        # stashes each compiled step program's shape signature here
        # (microseconds; no compile). perf_xray() / the flops profiler
        # materialize the cost/memory records on demand.
        self.xray = ProgramRegistry(self.telemetry,
                                    platform=jax.default_backend(),
                                    sample_every=0)

        self.training_dataloader = self.deepspeed_io(training_data) \
            if training_data else None

        # Parameters: client-provided pytree, module attribute, or lazy-init
        # at first forward from the batch shapes.
        self.params = self._extract_params(model, model_parameters)

        # Loss scaling (fp16 only; bf16/fp32 need none).
        self.loss_scaler = None
        if self.fp16_enabled():
            self.loss_scaler = CreateLossScaler(
                dynamic_scaling=self.dynamic_loss_scale(),
                static_loss_scale=self.loss_scale() or 1.0,
                dynamic_loss_args=self.dynamic_loss_scale_args())

        self._configure_optimizer(optimizer, model_parameters)
        self._configure_lr_scheduler(lr_scheduler)

        if self.pld_enabled():
            self.progressive_layer_drop = self._configure_progressive_layer_drop()

        self._configure_checkpointing()

        # TensorBoard monitor (reference engine.py:149-150), now a
        # telemetry.TensorBoardScalarWriter (lazy; warn-once no-op when
        # the extra is missing).
        self._tb_writer = None
        self._last_loss = None

        # Jitted program caches, keyed by static call signature.
        self._fwd_bwd_cache = {}
        self._update_fn = None
        self._fused_step_cache = {}
        self._cached_grads = None
        self._grad_acc = None

        # ZeRO sharding policy (applied when params exist).
        self._shardings_ready = False
        self._grad_constraint = None
        if self.params is not None:
            self._setup_shardings()

        if self.dump_state():
            self._dump_state()

    # ------------------------------------------------------------------ config

    def _config_world_size(self):
        """Data-parallel world size used for batch-triangle math. The
        PipelineEngine overrides this (its executor is dp=1 within stages)."""
        return mesh_lib.dp_size(self.mesh)

    def _peek_sequence_parallel(self, args, config_params):
        """(enabled, size) from the raw config source, read before the
        full DeepSpeedConfig parse (see __init__)."""
        from deepspeed_tpu.runtime.config import (
            get_sequence_parallel_enabled, get_sequence_parallel_size)

        raw = config_params
        config_file = getattr(args, "deepspeed_config", None) if args \
            else None
        if raw is None and config_file and os.path.isfile(config_file):
            with open(config_file) as f:
                raw = json.load(f)
        if not isinstance(raw, dict):
            return False, None
        return (get_sequence_parallel_enabled(raw),
                get_sequence_parallel_size(raw))

    def _setup_sequence_parallel_mesh(self, user_mesh, size):
        """Rebuild/validate the mesh for sequence parallelism: the token
        dim of every batch shards over a 'seq' axis (config
        "sequence_parallel": {"enabled": true, "size": N}). With a
        user-provided mesh the axis must already exist at the right size;
        the default mesh is rebuilt as dp x sp over the same devices."""
        if user_mesh is not None:
            have = mesh_lib.sp_size(user_mesh)
            if have <= 1:
                raise ValueError(
                    "sequence_parallel is enabled but the provided mesh "
                    "has no 'seq' axis (build_mesh(num_sp=...))")
            if size is not None and size != have:
                raise ValueError(
                    "sequence_parallel size {} != mesh 'seq' axis {}"
                    .format(size, have))
            return
        n = len(jax.devices())
        if size is None:
            size = n
        if n % size:
            raise ValueError(
                "sequence_parallel size {} does not divide {} devices"
                .format(size, n))
        self.mesh = mesh_lib.build_mesh(num_sp=size, num_dp=n // size)
        self.dp_world_size = self._config_world_size()
        self.world_size = self.dp_world_size

    def _configure_with_arguments(self, args, config_params):
        config_file = getattr(args, "deepspeed_config", None) if args else None
        assert config_file is not None or config_params is not None, \
            "DeepSpeed requires --deepspeed_config to specify configuration file"
        if config_file is not None and config_params is not None:
            # Mirrors the reference sanity check (engine.py:460-474): the two
            # config sources are mutually exclusive.
            raise ValueError(
                "Not sure how to proceed, we were given both a deepspeed_config "
                "file and a config_params dict — pass exactly one")
        if config_file is not None and not os.path.isfile(config_file):
            raise FileNotFoundError(
                "DeepSpeed config file not found: {}".format(config_file))
        return DeepSpeedConfig(config_file,
                               mpu=self.mpu,
                               param_dict=config_params,
                               world_size=self.dp_world_size)

    def _do_args_sanity_check(self, args):
        if args is not None and hasattr(args, "deepscale_config") and \
                args.deepscale_config is not None:
            logger.warning(
                "************ --deepscale_config is deprecated, please use "
                "--deepspeed_config ************")
            args.deepspeed_config = args.deepscale_config

    # config getters — mirror the reference's getter battery (engine.py:204-398)
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def steps_per_print(self):
        return self._config.steps_per_print

    def dump_state(self):
        return self._config.dump_state

    def wall_clock_breakdown(self):
        return self._config.wall_clock_breakdown

    def memory_breakdown(self):
        return self._config.memory_breakdown

    def sparse_gradients_enabled(self):
        return self._config.sparse_gradients_enabled

    def sequence_parallel_enabled(self):
        return self._config.sequence_parallel_enabled

    def sequence_parallel_size(self):
        return mesh_lib.sp_size(self.mesh)

    def zero_optimization(self):
        return self._config.zero_enabled

    def zero_optimization_stage(self):
        return self._config.zero_optimization_stage

    def zero_cpu_offload(self):
        return self._config.zero_config.cpu_offload

    def offload_timing(self):
        """Last _offload_step's phase timeline: stage_s (device->host wait
        + staging pack), adam_s (C++ host optimizer), upload_s (host->
        device dispatch), wall_s, chunks, and overlap_ratio = phase sum /
        wall (1.0 = fully serial; >1 = phases overlapped). None until an
        offload step has run."""
        return getattr(self, "_offload_timing", None)

    def zero_overlap_comm(self):
        return self._config.zero_config.overlap_comm

    def zero_reduce_scatter(self):
        return self._config.zero_config.reduce_scatter

    def zero_allgather_partitions(self):
        return self._config.zero_config.allgather_partitions

    def zero_reduce_bucket_size(self):
        return self._config.zero_config.reduce_bucket_size

    def zero_allgather_bucket_size(self):
        return self._config.zero_config.allgather_bucket_size

    def zero_contiguous_gradients(self):
        return self._config.zero_config.contiguous_gradients

    def zero_elastic_checkpoint(self):
        return self._config.zero_config.elastic_checkpoint

    def zero_load_from_fp32_weights(self):
        return self._config.zero_config.load_from_fp32_weights

    def fp16_enabled(self):
        return self._config.fp16_enabled

    def bfloat16_enabled(self):
        return self._config.bfloat16_enabled

    def amp_enabled(self):
        return self._config.amp_enabled

    def amp_params(self):
        return self._config.amp_params

    def loss_scale(self):
        return self._config.loss_scale

    def dynamic_loss_scale(self):
        return self._config.loss_scale == 0

    def initial_dynamic_scale(self):
        return self._config.initial_dynamic_scale

    def dynamic_loss_scale_args(self):
        return self._config.dynamic_loss_scale_args

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def _warn_onebit_clip_once(self, clip):
        """One-time notice that 1-bit Adam's compression phase operates on
        UNCLIPPED local grads (the reference compression phase does too,
        but its fp16 wrapper still unscales+clips first) — a configured
        clip value stops applying past the freeze boundary. Shared by the
        base engine's shard_map hot path and the pipeline engine's
        per-stage compressed update."""
        if clip > 0.0 and not getattr(self, "_onebit_clip_warned", False):
            self._onebit_clip_warned = True
            logger.warning(
                "1-bit Adam compressed phase ignores gradient_clipping=%s: "
                "clipping applies only during warmup; the quantization "
                "scale bounds the exchanged update instead.", clip)

    def optimizer_name(self):
        return self.client_optimizer.__class__.__name__ \
            if self.client_optimizer else self._config.optimizer_name

    def optimizer_params(self):
        return self._config.optimizer_params

    def optimizer_legacy_fusion(self):
        return self._config.optimizer_legacy_fusion

    def scheduler_name(self):
        return self._config.scheduler_name

    def scheduler_params(self):
        return self._config.scheduler_params

    def tensorboard_enabled(self):
        return self._config.tensorboard_enabled

    def tensorboard_output_path(self):
        return self._config.tensorboard_output_path

    def tensorboard_job_name(self):
        return self._config.tensorboard_job_name

    def _tensorboard_log_dir(self, name="DeepSpeedJobName", base=None):
        """Event-file directory (reference engine.py:247-272): under
        <output_path>/<job_name>, or the $DLWS/DLTS job dirs."""
        if self.tensorboard_output_path():
            return os.path.join(self.tensorboard_output_path(),
                                self.tensorboard_job_name() or name)
        summary_writer_dir_name = (self.tensorboard_job_name() or name)
        if base is None:
            base = os.path.join(os.path.expanduser("~"), "tensorboard")
        if "DLWS_JOB_ID" in os.environ:
            infra_job_id = os.environ["DLWS_JOB_ID"]
        elif "DLTS_JOB_ID" in os.environ:
            infra_job_id = os.environ["DLTS_JOB_ID"]
        else:
            infra_job_id = "unknown-job-id"
        return os.path.join(base, infra_job_id, summary_writer_dir_name)

    def _scalar_writer(self, name="DeepSpeedJobName", base=None):
        """Lazy telemetry.TensorBoardScalarWriter behind the
        ``tensorboard_*`` config keys. Degrades to a warn-once no-op
        when the tensorboard extra is missing — training never crashes
        over an exporter."""
        if self._tb_writer is None:
            self._tb_writer = TensorBoardScalarWriter(
                self._tensorboard_log_dir(name=name, base=base))
        return self._tb_writer

    def get_summary_writer(self, name="DeepSpeedJobName", base=None):
        """The raw SummaryWriter (reference API); raises when the
        tensorboard extra is unavailable — callers who can proceed
        without it should go through ``_scalar_writer()`` instead."""
        writer = self._scalar_writer(name=name, base=base)._get()
        if writer is None:
            raise RuntimeError(
                "tensorboard is unavailable (torch.utils.tensorboard "
                "failed to import or the log dir is unwritable)")
        return writer

    def _tensorboard_step_events(self):
        """Per-step scalars (reference engine.py:1011-1025: Train/Samples/
        train_loss, lr, loss_scale at each boundary step), plus the
        telemetry registry snapshot (phase-timer percentiles,
        samples_per_sec, step/sample gauges) under ``telemetry/``."""
        if not self.tensorboard_enabled() or self.global_rank != 0:
            return
        tb = self._scalar_writer()
        if not tb.available:  # warned once inside the writer
            return
        if self._last_loss is not None:
            tb.add_scalar("Train/Samples/train_loss",
                          float(jax.device_get(self._last_loss)),
                          self.global_samples)
        if self.optimizer is not None:
            tb.add_scalar("Train/Samples/lr", self.get_lr()[0],
                          self.global_samples)
        if self.loss_scaler is not None:
            tb.add_scalar("Train/Samples/loss_scale",
                          self.loss_scaler.loss_scale,
                          self.global_samples)
        tb.publish(self.telemetry, self.global_samples)
        tb.flush()

    def pld_enabled(self):
        return self._config.pld_enabled

    def pld_params(self):
        return self._config.pld_params

    def pld_theta(self):
        return self.pld_params()["theta"] if self.pld_params() else 1.0

    def pld_gamma(self):
        return self.pld_params()["gamma"] if self.pld_params() else 0.001

    def postscale_gradients(self):
        return not self._config.prescale_gradients

    def gradient_predivide_factor(self):
        return self._config.gradient_predivide_factor

    def sparse_attention(self):
        return self._config.sparse_attention

    def checkpoint_tag_validation_enabled(self):
        return self._config.checkpoint_tag_validation_enabled

    def checkpoint_tag_validation_fail(self):
        return self._config.checkpoint_tag_validation_fail

    def elasticity_enabled(self):
        return self._config.elasticity_enabled

    # --------------------------------------------------------------- model/opt

    def _extract_params(self, model, model_parameters):
        if model_parameters is not None:
            # flax's init returns {'params': ...}; accept either form.
            if isinstance(model_parameters, dict) and \
                    set(model_parameters.keys()) == {"params"}:
                return model_parameters["params"]
            return model_parameters
        if hasattr(model, "params") and model.params is not None:
            return model.params
        return None

    def _cast_to_compute(self, params):
        if self.compute_dtype == jnp.float32:
            return params
        dtype = self.compute_dtype
        return jax.tree_util.tree_map(
            lambda p: p.astype(dtype) if jnp.issubdtype(p.dtype, jnp.floating) else p,
            params)

    def _configure_optimizer(self, client_optimizer, model_parameters):
        if client_optimizer is not None:
            self.optimizer = client_optimizer
            log_dist("Using client Optimizer as basic optimizer", ranks=[0])
            if self.zero_cpu_offload() and not self._offload_mode():
                logger.warning(
                    "zero_optimization.cpu_offload is set but the client "
                    "optimizer is not DeepSpeedCPUAdam — optimizer state "
                    "stays in HBM (no offload).")
        elif self._config.optimizer_name is not None:
            self.optimizer = self._configure_basic_optimizer(model_parameters)
            log_dist("Using DeepSpeed Optimizer param name {} as basic optimizer"
                     .format(self.optimizer_name()), ranks=[0])
        else:
            self.optimizer = None
            return

        self.opt_state = None
        self._offload = None  # host-state bookkeeping (ZeRO-Offload tier)
        self._offload_pre_fn = None  # jitted device-side unscale+clip
        self._embed_paths_cache = None  # sparse-grad embedding leaf paths
        if self.params is not None and not self._offload_mode():
            self.opt_state = self.optimizer.init_state(self.params)

    def _offload_mode(self):
        from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
        from deepspeed_tpu.ops.lamb.cpu_lamb import DeepSpeedCPULamb
        return isinstance(self.optimizer, (DeepSpeedCPUAdam,
                                           DeepSpeedCPULamb))

    def _configure_basic_optimizer(self, model_parameters):
        """Optimizer factory table (reference engine.py:577-617)."""
        optimizer_parameters = dict(self.optimizer_params() or {})
        optimizer_parameters.pop("torch_adam", None)
        optimizer_parameters.pop("adam_w_mode", None)
        name = self._config.optimizer_name
        if name in [ADAM_OPTIMIZER, ADAMW_OPTIMIZER]:
            adam_w_mode = (name == ADAMW_OPTIMIZER) or \
                (self.optimizer_params() or {}).get("adam_w_mode", name == ADAMW_OPTIMIZER)
            if self.zero_cpu_offload():
                # ZeRO-Offload decision matrix (reference engine.py:577-617):
                # cpu_offload selects DeepSpeedCPUAdam; optimizer state lives
                # in host DRAM and the update runs in the C++ op.
                from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
                return DeepSpeedCPUAdam(model_params=model_parameters,
                                        adamw_mode=adam_w_mode,
                                        **optimizer_parameters)
            return FusedAdam(params=model_parameters,
                             adam_w_mode=adam_w_mode,
                             **optimizer_parameters)
        elif name == LAMB_OPTIMIZER:
            if self.zero_cpu_offload():
                # Host LAMB tier (the reference's offload matrix is
                # Adam-only, engine.py:577-617; on the TPU-VM host tier
                # LAMB composes the same way via csrc/lamb/cpu_lamb.cpp).
                from deepspeed_tpu.ops.lamb.cpu_lamb import DeepSpeedCPULamb
                host_keys = ("lr", "bias_correction", "betas", "eps",
                             "weight_decay", "max_coeff", "min_coeff",
                             "amsgrad")
                dropped = [k for k in optimizer_parameters
                           if k not in host_keys]
                if dropped:
                    # Device-only knobs (eps_inside_sqrt, max_grad_norm):
                    # warn, don't silently change semantics.
                    logger.warning(
                        "Lamb params %s are not supported by the host "
                        "(cpu_offload) tier and are ignored", dropped)
                return DeepSpeedCPULamb(
                    model_params=model_parameters,
                    **{k: v for k, v in optimizer_parameters.items()
                       if k in host_keys})
            return FusedLamb(params=model_parameters, **optimizer_parameters)
        elif name == ONEBIT_ADAM_OPTIMIZER:
            if self.zero_cpu_offload():
                raise ValueError(
                    "zero_optimization.cpu_offload requires an Adam/AdamW "
                    "optimizer (got {}); the host tier is DeepSpeedCPUAdam"
                    .format(name))
            from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdam
            return OnebitAdam(params=model_parameters, deepspeed=self,
                              **optimizer_parameters)
        else:
            if not self._config.zero_allow_untested_optimizer and \
                    self.zero_optimization():
                raise ValueError(
                    "ZeRO with untested optimizer '{}' requires "
                    "zero_allow_untested_optimizer".format(name))
            raise ValueError("Unknown optimizer: {}".format(name))

    def _configure_lr_scheduler(self, client_lr_scheduler):
        """Config scheduler takes precedence unless client passed one
        (reference engine.py:400-446)."""
        scheduler_name = self.scheduler_name()
        if scheduler_name is not None and self.optimizer is not None:
            scheduler = getattr(lr_schedules, scheduler_name, None)
            assert scheduler is not None, \
                "DeepSpeed does not recognize LR scheduler {}".format(scheduler_name)
            scheduler_params = self.scheduler_params() or {}
            self.lr_scheduler = scheduler(self.optimizer, **scheduler_params)
            log_dist("DeepSpeed using configured LR scheduler = {}".format(
                scheduler_name), ranks=[0])
        else:
            if callable(client_lr_scheduler) and self.optimizer is not None:
                self.lr_scheduler = client_lr_scheduler(self.optimizer)
            else:
                self.lr_scheduler = client_lr_scheduler
        log_dist("DeepSpeed LR Scheduler = {}".format(self.lr_scheduler), ranks=[0])

    def _configure_checkpointing(self):
        """Push an explicit activation_checkpointing config block into the
        module-level checkpointing state. TPU-build convenience: the reference
        leaves configure() to the user (Megatron calls it); here ds_config is
        the single source of truth, but only when the block is present — a
        user's earlier direct configure() call is never clobbered."""
        from deepspeed_tpu.runtime.activation_checkpointing.config import ACT_CHKPT
        if ACT_CHKPT not in (self._config._param_dict or {}):
            return
        from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
        cfg = self._config.activation_checkpointing_config
        checkpointing.configure(
            mpu_=self.mpu,
            partition_activations=cfg.partition_activations,
            contiguous_checkpointing=cfg.contiguous_memory_optimization,
            num_checkpoints=cfg.number_checkpoints,
            checkpoint_in_cpu=cfg.cpu_checkpointing,
            synchronize=cfg.synchronize_checkpoint_boundary,
            profile=cfg.profile,
            mesh_=self.mesh)

    def _configure_progressive_layer_drop(self):
        return ProgressiveLayerDrop(theta=self.pld_theta(), gamma=self.pld_gamma())

    def _setup_shardings(self):
        self._embed_paths_cache = None  # params (re)set: recompute lazily
        stage = self.zero_optimization_stage() if self.zero_optimization() else 0
        self.param_sharding, self.grad_sharding, opt_fn = \
            mesh_lib.zero_shardings(
                self.mesh, self.params, stage,
                tp_rules=getattr(self.module, "tp_rules", None))
        if self.opt_state is not None and not self._offload_mode():
            moment_sh = {
                "step": mesh_lib.replicated(self.mesh),
                "exp_avg": opt_fn(self.opt_state["exp_avg"]),
                "exp_avg_sq": opt_fn(self.opt_state["exp_avg_sq"]),
            }
            # Extra optimizer state (e.g. OnebitAdam error-feedback buffers)
            # follows the same ZeRO policy as the moments — error buffers are
            # elementwise state and must not stay replicated under ZeRO.
            for key in self.opt_state:
                if key not in moment_sh:
                    moment_sh[key] = opt_fn(self.opt_state[key])
            if self._onebit_spmd_eligible():
                # Per-worker error-feedback rows live with their worker:
                # row r is rank r's private state in the two-phase
                # exchange (compressed_allreduce), so the leading [W] dim
                # shards over 'data' and the shard_map hot path sees only
                # its own row.
                row_sh = mesh_lib.NamedSharding(
                    self.mesh, mesh_lib.P(mesh_lib.DATA_AXIS))
                for key in ("worker_error", "server_error"):
                    if key in self.opt_state:
                        moment_sh[key] = jax.tree_util.tree_map(
                            lambda _: row_sh, self.opt_state[key])
            self.opt_state_sharding = moment_sh
            # Place state according to policy now (one-time reshard).
            self.opt_state = jax.device_put(self.opt_state, moment_sh)
        self.params = jax.device_put(self.params, self.param_sharding)
        # ZeRO-2/3 semantics (reference stage2.py:675-738): gradients are
        # REDUCE-SCATTERED to their owner shard, never materialized
        # replicated. Enforced as a GSPMD constraint inside every grad-
        # producing program; XLA lowers the cross-replica sum to
        # reduce-scatter instead of all-reduce.
        self._grad_constraint = self.grad_sharding if stage >= 2 else None
        self._shardings_ready = True

    # ------------------------------------------------------------------- RNG

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ------------------------------------------------------------ data loading

    def deepspeed_io(self,
                     dataset,
                     batch_size=None,
                     route=ROUTE_TRAIN,
                     pin_memory=True,
                     data_sampler=None,
                     collate_fn=None,
                     num_local_io_workers=None):
        """Build the sharded dataloader (reference engine.py:706-747).

        Single-controller JAX: one loader yields the GLOBAL micro-batch
        (micro_batch_per_chip × dp_size); the engine shards it over the 'data'
        mesh axis at dispatch.
        """
        if batch_size is None:
            batch_size = self.train_micro_batch_size_per_gpu() * self.dp_world_size
        collate_fn = collate_fn or self.collate_fn
        return DeepSpeedDataLoader(dataset=dataset,
                                   batch_size=batch_size,
                                   local_rank=self.local_rank,
                                   data_parallel_world_size=1,
                                   data_parallel_rank=0,
                                   collate_fn=collate_fn,
                                   num_local_io_workers=num_local_io_workers,
                                   data_sampler=data_sampler)

    # -------------------------------------------------------------- train/eval

    def train(self, mode=True):
        self.warn_unscaled_loss = True
        self.training = mode

    def eval(self):
        self.warn_unscaled_loss = True
        self.training = False

    def __call__(self, *inputs, **kwargs):
        return self.forward(*inputs, **kwargs)

    # --------------------------------------------------------------- forward

    def _split_kwargs(self, kwargs):
        """Traced (numeric) vs static (bool/str/None) kwargs for jit caching."""
        static, traced = {}, {}
        for k, v in kwargs.items():
            if isinstance(v, bool) or isinstance(v, str) or v is None:
                static[k] = v
            elif isinstance(v, (int, float)):
                traced[k] = jnp.asarray(v)
            else:
                traced[k] = v
        return static, traced

    def _embedding_grad_paths(self):
        """Leaf paths of embedding tables (flax nn.Embed 'embedding' params)
        — the analogue of the reference's nn.Embedding scan
        (engine.py:180-185) that decides which grads go through the sparse
        index/value exchange."""
        if self.params is None:
            return frozenset()
        if self._embed_paths_cache is not None:
            return self._embed_paths_cache
        # flax nn.Embed stores its table as '<module>/embedding'; the repo's
        # own models use raw params 'wte' (gpt2.py:149) and BERT-style
        # '*_embeddings' modules. Tables whose grads turn out dense at
        # runtime (tied softmax heads) fall back inside
        # sparse_grad_exchange, so a broad match is safe.
        embed_names = {"embedding", "wte", "word_embeddings"}
        paths = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self.params)[0]:
            names = [str(getattr(p, "key", getattr(p, "name", "")))
                     for p in path]
            if getattr(leaf, "ndim", 0) >= 2 and any(
                    n in embed_names or n.endswith("_embeddings")
                    for n in names):
                paths.append(tuple(str(p) for p in path))
        self._embed_paths_cache = frozenset(paths)
        return self._embed_paths_cache

    def _get_fwd_bwd(self, n_args, static_kwargs, traced_keys, train):
        sparse_embed = bool(
            train and self.sparse_gradients_enabled()
            and mesh_lib.dp_size(self.mesh) > 1
            and self._embedding_grad_paths())
        sp_parallel = bool(self.sequence_parallel_enabled()
                           and mesh_lib.sp_size(self.mesh) > 1
                           and not getattr(self, "_force_serial_fwd_bwd",
                                           False))
        if sp_parallel and sparse_embed:
            raise NotImplementedError(
                "sequence_parallel cannot be combined with sparse_gradients")
        key = (n_args, tuple(sorted(static_kwargs.items())),
               tuple(sorted(traced_keys)), train, self.compute_dtype.__name__,
               self._grad_constraint is not None, sparse_embed, sp_parallel)
        if key in self._fwd_bwd_cache:
            return self._fwd_bwd_cache[key]
        grad_constraint = self._grad_constraint

        cast = self._cast_to_compute
        setup = self._module_apply_setup()
        apply_fn, accepts_deterministic = setup
        make_loss = self._make_loss_fn(static_kwargs, train, setup=setup)

        def loss_and_grads(params, args, traced_kwargs, rng, scale):
            loss_fn = make_loss(args, traced_kwargs, rng, scale)
            (_, out), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            if grad_constraint is not None:
                grads = jax.lax.with_sharding_constraint(grads, grad_constraint)
            return out, grads

        if sparse_embed:
            jitted = self._build_sparse_grad_fwd_bwd(
                static_kwargs=static_kwargs, cast=cast, apply_fn=apply_fn,
                accepts_deterministic=accepts_deterministic,
                grad_constraint=grad_constraint)
        elif sp_parallel:
            jitted = self._build_sequence_parallel_fwd_bwd(
                static_kwargs=static_kwargs, cast=cast, apply_fn=apply_fn,
                accepts_deterministic=accepts_deterministic,
                grad_constraint=grad_constraint, train=train)
        else:
            jitted = jax.jit(loss_and_grads)
        self._fwd_bwd_cache[key] = jitted
        return jitted

    def _module_apply_setup(self):
        """(apply_fn, accepts_deterministic) for the wrapped module —
        shared by every fwd+bwd program builder. Training must actually
        enable dropout: flax modules gate it on a `deterministic` kwarg
        defaulting True, so builders pass False when the model accepts it
        and the caller didn't choose explicitly."""
        module = self.module
        apply_fn = module.apply if hasattr(module, "apply") else module
        accepts_deterministic = False
        try:
            import inspect
            accepts_deterministic = "deterministic" in \
                inspect.signature(type(module).__call__).parameters
        except (TypeError, ValueError):
            pass
        return apply_fn, accepts_deterministic

    def _make_loss_fn(self, static_kwargs, train, setup=None):
        """Factory for the scaled-loss closure shared by the plain and
        grad-streaming fwd+bwd builders — ONE place owns the module
        call / rng / deterministic conventions. ``setup`` lets a caller
        that already ran _module_apply_setup pass it through."""
        cast = self._cast_to_compute
        apply_fn, accepts_deterministic = setup or self._module_apply_setup()

        def make(args, traced_kwargs, rng, scale):
            def loss_fn(p):
                cp = cast(p)
                call_kwargs = dict(static_kwargs)
                call_kwargs.update(traced_kwargs)
                if train:
                    if accepts_deterministic:
                        call_kwargs.setdefault("deterministic", False)
                    out = apply_fn({"params": cp}, *args,
                                   rngs={"dropout": rng}, **call_kwargs)
                else:
                    out = apply_fn({"params": cp}, *args, **call_kwargs)
                loss = out[0] if isinstance(out, tuple) else out
                return loss * scale, out

            return loss_fn

        return make

    def _stream_grads_active(self):
        """True when the offload tier should stream gradients to host
        during backward instead of materializing the full grad tree."""
        return self._offload_mode() and \
            bool(getattr(self._config.zero_config, "stream_gradients",
                         False))

    def _stream_sink(self, idx, g):
        """io_callback target: write one gradient leaf into the host
        staging buffer (fp32, master layout). Leaves occupy disjoint
        spans, so unordered callbacks may land concurrently."""
        off = self._offload
        i = int(idx)
        o, size = int(off["offsets"][i]), off["sizes"][i]
        off["stream_g"][o:o + size] = np.asarray(g, np.float32).ravel()
        return np.int32(0)

    def _get_streaming_fwd_bwd(self, n_args, static_kwargs, traced_keys,
                               train):
        """fwd+bwd program for the grad-streaming offload tier.

        The gradient tree never becomes program OUTPUT: each leaf is
        consumed inside the program by an io_callback that copies it to
        the host staging buffer, so XLA can free it as the backward
        proceeds, and the param inputs are donated (they are
        re-materialized from the host master at step() anyway). Device
        peak drops from ~4 bytes/param (bf16 params + full bf16 grad
        outputs) toward ~2 — the reference's ZeRO-Offload streams grad
        buckets to pinned CPU memory during backward for the same reason
        (stage2.py:740-817). Only per-leaf squared norms leave the
        program (clipping + overflow)."""
        key = ("stream", n_args, tuple(sorted(static_kwargs.items())),
               tuple(sorted(traced_keys)), train)
        if key in self._fwd_bwd_cache:
            return self._fwd_bwd_cache[key]
        from jax.experimental import io_callback

        make_loss = self._make_loss_fn(static_kwargs, train)
        sink = self._stream_sink

        def loss_and_stream(params, args, traced_kwargs, rng, scale):
            loss_fn = make_loss(args, traced_kwargs, rng, scale)
            _, vjp_fn, out = jax.vjp(loss_fn, params, has_aux=True)
            (grads,) = vjp_fn(jnp.float32(1.0))
            sqs, toks = [], []
            for i, g in enumerate(jax.tree_util.tree_leaves(grads)):
                sqs.append(jnp.sum(g.astype(jnp.float32) ** 2))
                # Unordered: leaves write disjoint host spans. The token
                # is folded into an output so DCE keeps the callback.
                toks.append(io_callback(
                    sink, jax.ShapeDtypeStruct((), jnp.int32),
                    jnp.int32(i), g))
            return out, jnp.stack(sqs), jnp.stack(toks).sum()

        jitted = jax.jit(loss_and_stream, donate_argnums=0)
        self._fwd_bwd_cache[key] = jitted
        return jitted

    def _build_sequence_parallel_fwd_bwd(self, static_kwargs, cast, apply_fn,
                                         accepts_deterministic,
                                         grad_constraint, train):
        """fwd+bwd program with SEQUENCE parallelism: tokens shard over the
        'seq' mesh axis under shard_map; the model runs on its local token
        slice (ring attention mixes across shards — the model must be
        sequence-shardable, e.g. GPT2Config(sequence_parallel_axis='seq')),
        grads psum over 'seq' and pmean over 'data'. Beyond the reference
        (v0.3.10 has no sequence parallelism, SURVEY §0)."""
        from functools import partial

        from deepspeed_tpu.utils.jax_compat import shard_map

        mesh = self.mesh
        dp = mesh_lib.dp_size(mesh)
        sp = mesh_lib.sp_size(mesh)
        module_cfg = getattr(self.module, "config", None)
        if getattr(module_cfg, "sequence_parallel_axis", None) != \
                mesh_lib.SEQ_AXIS:
            raise ValueError(
                "sequence_parallel is enabled but the model is not "
                "sequence-shardable: its config must set "
                "sequence_parallel_axis='{}' (attention must mix tokens "
                "across shards — silently sharding a serial model would "
                "train a different function)".format(mesh_lib.SEQ_AXIS))

        def loss_and_grads(params, args, traced_kwargs, rng, scale):
            P_ = jax.sharding.PartitionSpec

            def check(x):
                # Silent down-sharding would run the model's SP paths on
                # wrong decompositions (full sequences treated as shards,
                # or non-token dims sliced): every batch array must split
                # exactly — batch over dp, and tokens (dim 1 of any rank>=2
                # array) over sp.
                shape = getattr(x, "shape", ())
                if len(shape) >= 1 and shape[0] % dp:
                    raise ValueError(
                        "sequence_parallel: batch dim {} of shape {} not "
                        "divisible by dp={}".format(shape[0], shape, dp))
                if len(shape) >= 2 and shape[1] % sp:
                    raise ValueError(
                        "sequence_parallel: token dim {} of shape {} not "
                        "divisible by sp={} (all rank>=2 batch arrays are "
                        "token-sharded on dim 1)".format(
                            shape[1], shape, sp))
                return x

            jax.tree_util.tree_map(check, (args, traced_kwargs))

            def arg_spec(x):
                return mesh_lib.batch_partition_spec(x, dp, sp)

            arg_specs = jax.tree_util.tree_map(arg_spec, args)
            kw_specs = jax.tree_util.tree_map(arg_spec, traced_kwargs)

            @partial(shard_map, mesh=mesh,
                     in_specs=(P_(), arg_specs, kw_specs, P_(), P_()),
                     out_specs=(P_(), P_()), check_vma=False)
            def spmd(params, largs, lkwargs, rng, scale):
                rng = jax.random.fold_in(
                    rng, jax.lax.axis_index(mesh_lib.DATA_AXIS) * sp
                    + jax.lax.axis_index(mesh_lib.SEQ_AXIS))

                def loss_fn(p):
                    cp = cast(p)
                    call_kwargs = dict(static_kwargs)
                    call_kwargs.update(lkwargs)
                    if train and accepts_deterministic:
                        call_kwargs.setdefault("deterministic", False)
                    rngs = {"dropout": rng} if train else {}
                    out = apply_fn({"params": cp}, *largs,
                                   rngs=rngs, **call_kwargs)
                    if isinstance(out, tuple):
                        raise NotImplementedError(
                            "sequence_parallel requires the model to "
                            "return the scalar loss (auxiliary outputs "
                            "would be silently dropped)")
                    return out * scale, out

                (_, out), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                # The model's internal psum already made the loss uniform
                # over 'seq'; average over 'data' for the global batch mean.
                out = jax.lax.pmean(out, mesh_lib.DATA_AXIS)
                # shard_map autodiff is collective-aware: differentiating
                # THROUGH the model's psum/ppermute ties the shards, so
                # each device's grad is already the FULL gradient of its
                # data-shard's loss (psum's transpose is psum) — pmean
                # over 'seq' (deduplicate), pmean over 'data' (global
                # batch mean). A psum over 'seq' here would scale grads by
                # sp — invisible to Adam (scale-invariant) but wrong for
                # clipping/SGD; pg_correctness_test now guards this
                # against the forced-serial reference.
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(
                        jax.lax.pmean(g, mesh_lib.SEQ_AXIS),
                        mesh_lib.DATA_AXIS),
                    grads)
                return out, grads

            out, grads = spmd(params, args, traced_kwargs, rng, scale)
            if grad_constraint is not None:
                grads = jax.lax.with_sharding_constraint(
                    grads, grad_constraint)
            return out, grads

        return jax.jit(loss_and_grads)

    def _build_sparse_grad_fwd_bwd(self, static_kwargs, cast, apply_fn,
                                   accepts_deterministic, grad_constraint):
        """fwd+bwd program with SPARSE embedding-gradient exchange: the loss
        is computed per data shard under shard_map, dense grads are psum'd,
        and embedding-table grads are exchanged as (row-index, row-value)
        pairs bounded by the shard's token count (reference CSR sparse-grad
        DP, engine.py:180-185,1186-1242)."""
        from functools import partial

        from deepspeed_tpu.utils.jax_compat import shard_map

        from deepspeed_tpu.runtime.csr_tensor import sparse_grad_exchange

        mesh = self.mesh
        dp = mesh_lib.dp_size(mesh)
        embed_paths = self._embedding_grad_paths()

        def loss_and_grads(params, args, traced_kwargs, rng, scale):
            def batch_spec(x):
                return mesh_lib.batch_partition_spec(x, dp)

            arg_specs = jax.tree_util.tree_map(batch_spec, args)
            kw_specs = jax.tree_util.tree_map(batch_spec, traced_kwargs)
            P_ = jax.sharding.PartitionSpec

            @partial(shard_map, mesh=mesh,
                     in_specs=(P_(), arg_specs, kw_specs, P_(), P_()),
                     out_specs=(P_(), P_()), check_vma=False)
            def spmd(params, largs, lkwargs, rng, scale):
                rng = jax.random.fold_in(
                    rng, jax.lax.axis_index(mesh_lib.DATA_AXIS))

                def loss_fn(p):
                    cp = cast(p)
                    call_kwargs = dict(static_kwargs)
                    call_kwargs.update(lkwargs)
                    if accepts_deterministic:
                        call_kwargs.setdefault("deterministic", False)
                    out = apply_fn({"params": cp}, *largs,
                                   rngs={"dropout": rng}, **call_kwargs)
                    if isinstance(out, tuple):
                        # Loud, not silent: the sparse path returns only the
                        # pmean'd scalar, so auxiliary outputs would be
                        # dropped behind the user's back.
                        raise NotImplementedError(
                            "sparse_gradients with data parallelism "
                            "requires a scalar-loss model output; this "
                            "model returns a tuple — disable "
                            "sparse_gradients or return only the loss")
                    loss = out
                    assert getattr(loss, "ndim", 0) == 0, \
                        "sparse_gradients requires a scalar loss output"
                    return loss * scale, loss

                (_, loss), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                # Token budget = this shard's integer elements (ids+labels):
                # an embedding grad has at most one nonzero row per token.
                k = sum(int(np.prod(l.shape)) for l in
                        jax.tree_util.tree_leaves((largs, lkwargs))
                        if jnp.issubdtype(l.dtype, jnp.integer)) or None

                def reduce_leaf(path, g):
                    names = tuple(str(p) for p in path)
                    if names in embed_paths and k is not None:
                        return sparse_grad_exchange(
                            g, mesh_lib.DATA_AXIS, k, average=True)
                    return jax.lax.pmean(g, mesh_lib.DATA_AXIS)

                grads = jax.tree_util.tree_map_with_path(reduce_leaf, grads)
                loss = jax.lax.pmean(loss, mesh_lib.DATA_AXIS)
                return loss, grads

            loss, grads = spmd(params, args, traced_kwargs, rng, scale)
            if grad_constraint is not None:
                grads = jax.lax.with_sharding_constraint(grads,
                                                         grad_constraint)
            return loss, grads

        return jax.jit(loss_and_grads)

    def forward(self, *inputs, **kwargs):
        """Run forward AND backward as one fused XLA program; cache grads.

        Returns the module output (the loss, by DeepSpeed convention). The
        cached grads are consumed by :meth:`backward`.
        """
        if self.flops_profiler_enabled() and \
                self.global_steps == self.flops_profiler_start_step() and \
                self.global_rank == 0:
            self._start_flops_profiler()

        if self.progressive_layer_drop:
            kwargs.update(self.progressive_layer_drop.get_state())

        if self.wall_clock_breakdown():
            self.timers("forward_microstep").start()
            self.timers("forward").start()

        inputs = tuple(jnp.asarray(x) if isinstance(x, np.ndarray) else x
                       for x in inputs)
        inputs = mesh_lib.shard_batch(self.mesh, inputs)

        if self.params is None:
            # Lazy init from batch shapes (flax idiom; the reference gets
            # params from the constructed torch module instead).
            init_kwargs = {k: v for k, v in kwargs.items()}
            variables = self.module.init(
                {"params": self._next_rng(), "dropout": self._next_rng()},
                *inputs, **init_kwargs)
            self.params = variables["params"]
            if self.optimizer is not None and not self._offload_mode():
                self.opt_state = self.optimizer.init_state(self.params)
            self._setup_shardings()

        if self.training:
            self.tput_timer.start()

        static_kwargs, traced_kwargs = self._split_kwargs(kwargs)
        scale = jnp.float32(self.loss_scaler.loss_scale) if self.loss_scaler \
            else jnp.float32(1.0)
        step_rng = self._next_rng()
        if self.training and self._stream_grads_active():
            assert self.gradient_accumulation_steps() == 1, \
                "stream_gradients requires gradient_accumulation_steps=1 " \
                "(params are donated per backward)"
            assert len(self.mesh.devices.flat) == 1, \
                "stream_gradients targets single-chip offload capacity; " \
                "use plain cpu_offload on multi-device meshes"
            assert not pg_correctness_test, \
                "pg_correctness_test needs materialized gradients — " \
                "disable stream_gradients to cross-check"
            if self._offload is None:
                self._init_offload()
            if "stream_g" not in self._offload:
                self._offload["stream_g"] = np.empty(
                    int(self._offload["master"].size), np.float32)
            fwd_bwd = self._get_streaming_fwd_bwd(
                len(inputs), static_kwargs, traced_kwargs.keys(),
                self.training)
            out, sqnorms, token = fwd_bwd(self.params, inputs,
                                          traced_kwargs, step_rng, scale)
            self._cached_grads = _StreamedGrads(sqnorms, token)
            if self.wall_clock_breakdown():
                self.timers("forward").stop()
                self.timers("forward_microstep").stop()
            return out
        fwd_bwd = self._get_fwd_bwd(len(inputs), static_kwargs,
                                    traced_kwargs.keys(), self.training)
        out, grads = fwd_bwd(self.params, inputs, traced_kwargs,
                             step_rng, scale)
        if pg_correctness_test and self.training:
            self._pg_correctness_check(inputs, static_kwargs, traced_kwargs,
                                       step_rng, scale, grads)
        if getattr(self, "flops_profiler", None) is not None and \
                self.flops_profiler.started:
            # Exact program cost from XLA (fwd+bwd in one program); the
            # example batch feeds the per-module tabulation report.
            if self.flops_profiler._example_args is None:
                self.flops_profiler.set_example_batch(*inputs)
            # Constant key: observe() only needs shapes/dtypes for lowering;
            # splitting the engine RNG here would make profiling perturb
            # training.
            self.flops_profiler.observe(fwd_bwd, self.params, inputs,
                                        traced_kwargs,
                                        jax.random.PRNGKey(0), scale)
        if self.training:
            self._cached_grads = grads

        if self.wall_clock_breakdown():
            self.timers("forward").stop()
            self.timers("forward_microstep").stop()

        if self.flops_profiler_enabled() and \
                self.global_steps == self.flops_profiler_end_step() and \
                self.global_rank == 0:
            self._stop_flops_profiler()

        return out

    def _pg_correctness_check(self, inputs, static_kwargs, traced_kwargs,
                              rng, scale, sharded_grads):
        """Cross-check sharded-path gradients against an INDEPENDENT
        reference program: fp32 compute, no ZeRO sharding constraints,
        fully replicated data (reference pg_correctness_test,
        stage2.py:23-25: deterministic fp32 allreduce so partitioned grads
        can be verified against unpartitioned ones). Forcing fp32 keeps the
        reference program distinct even at stage 0/1, where the sharded
        path has no constraint either — comparing a program against itself
        would be vacuous. Raises on mismatch."""
        if self.loss_scaler is not None and \
                bool(jax.device_get(jit_has_overflow(sharded_grads))):
            # fp16 overflow step: by design recoverable — the scaler's step
            # path skips it and shrinks the scale; inf/nan grads can never
            # match the fp32 reference, so checking would turn recovery
            # into a crash. WITHOUT a scaler there is no recovery path, so
            # non-finite grads fall through to the check and raise.
            return
        saved_constraint = self._grad_constraint
        saved_dtype = self.compute_dtype
        self._grad_constraint = None
        self.compute_dtype = jnp.float32
        # Force the plain (non-shard_map) program: under sequence
        # parallelism the reference must be the SERIAL function — building
        # the same SP decomposition twice would make the comparison
        # vacuous (an SP-specific psum/label-shift bug matches itself).
        self._force_serial_fwd_bwd = True
        try:
            ref_fn = self._get_fwd_bwd(len(inputs), static_kwargs,
                                       traced_kwargs.keys(), True)
            rep = mesh_lib.replicated(self.mesh)
            rep_params = jax.device_put(self.params, rep)
            rep_inputs = jax.device_put(inputs, rep)
            _, ref_grads = ref_fn(rep_params, rep_inputs, traced_kwargs,
                                  rng, scale)
        finally:
            self._grad_constraint = saved_constraint
            self.compute_dtype = saved_dtype
            self._force_serial_fwd_bwd = False
        tol = 2e-2 if saved_dtype != jnp.float32 else 1e-4
        if self.sequence_parallel_enabled() and \
                mesh_lib.sp_size(self.mesh) > 1:
            # SP is a genuinely different decomposition (ring-merge
            # softmax vs one-block attention): fp32 rounding scatter
            # reaches ~1e-3 elementwise while gradient NORMS agree to
            # ~0.1% — an sp-times scale bug still exceeds this by ~8x.
            tol = max(tol, 5e-3)
        for (path, a), b in zip(
                jax.tree_util.tree_flatten_with_path(sharded_grads)[0],
                jax.tree_util.tree_leaves(ref_grads)):
            a = np.asarray(jax.device_get(a), np.float32)
            b = np.asarray(jax.device_get(b), np.float32)
            if not np.allclose(a, b, rtol=tol, atol=tol):
                raise RuntimeError(
                    "pg_correctness_test: sharded gradient for {} diverges "
                    "from the fp32 replicated reference (max abs diff "
                    "{})".format(jax.tree_util.keystr(path),
                                 np.abs(a - b).max()))

    # --------------------------------------------------------------- backward

    def allreduce_gradients(self, bucket_size=MEMORY_OPT_ALLREDUCE_SIZE):
        """No-op on TPU: gradient reduction is a GSPMD sharding constraint
        inserted by XLA (reference engine.py:832-846 does explicit bucketed
        allreduce). Kept for API parity."""
        return None

    def csr_allreduce_no_retain(self, csr_list):
        """Average a list of CSRTensors across data-parallel workers
        (reference csr_allreduce_no_retain, engine.py:1186-1200).

        Single-controller GSPMD: the per-worker dense grads were already
        averaged inside the jitted program, so the host-visible CSR values
        are global — only the 1/N scaling semantics remain. Multi-controller
        shard_map pipelines use runtime.csr_tensor.csr_allreduce directly.
        """
        from deepspeed_tpu.runtime.csr_tensor import CSRTensor
        return [CSRTensor(indices=c.indices, values=c.values,
                          dense_size=c.dense_size) for c in csr_list]

    def sparse_allreduce_bucket(self, bucket):
        return self.csr_allreduce_no_retain(bucket)

    def backward(self, loss, allreduce_gradients=True, release_loss=False):
        """Accumulate the gradients computed in :meth:`forward`.

        The reference scales loss by 1/gas and runs autograd
        (engine.py:848-927); here the grads already exist (fused fwd+bwd), so
        backward just folds them into the accumulation buffer.
        """
        assert self._cached_grads is not None, \
            "backward() called without a prior forward()"
        self._last_loss = loss

        if self.wall_clock_breakdown():
            self.timers("backward_microstep").start()
            self.timers("backward").start()

        gas = self.gradient_accumulation_steps()
        grads = self._cached_grads
        self._cached_grads = None

        if isinstance(grads, _StreamedGrads):
            # Already staged on host during the fused backward; gas == 1
            # is enforced at forward, so there is nothing to fold.
            self._grad_acc = grads
            if self.wall_clock_breakdown():
                self.timers("backward").stop()
                self.timers("backward_microstep").stop()
            return loss

        if self._grad_acc is None:
            if gas > 1:
                self._grad_acc = jax.tree_util.tree_map(
                    lambda g: g / gas, grads)
            else:
                self._grad_acc = grads
        else:
            self._grad_acc = jax.tree_util.tree_map(
                lambda a, g: a + g / gas, self._grad_acc, grads)

        if self.wall_clock_breakdown():
            self.timers("backward").stop()
            self.timers("backward_microstep").stop()

        return loss

    # ------------------------------------------------------------------- step

    def is_gradient_accumulation_boundary(self):
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def zero_grad(self):
        self._grad_acc = None
        self._cached_grads = None

    def get_lr(self):
        return [g["lr"] for g in self.optimizer.param_groups]

    def set_lr(self, lr):
        for g in self.optimizer.param_groups:
            g["lr"] = lr

    def get_mom(self):
        return [g.get("betas", (0.0, 0.0))[0] for g in self.optimizer.param_groups]

    def _get_update_fn(self):
        if self._update_fn is not None:
            return self._update_fn
        optimizer = self.optimizer
        clip = self.gradient_clipping()

        def update(params, opt_state, grads, inv_scale, lr, beta1, beta2):
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) * inv_scale, grads)
            if clip > 0.0:
                grads, _ = clip_grad_norm_(grads, clip)
            return optimizer.update(params, grads, opt_state, lr=lr,
                                    betas=(beta1, beta2))

        out_shardings = None
        if self._shardings_ready:
            out_shardings = (self.param_sharding, self.opt_state_sharding)
        self._update_fn = jax.jit(update, out_shardings=out_shardings,
                                  donate_argnums=(0, 1))
        return self._update_fn

    def _take_model_step(self, lr_kwargs=None):
        grads = self._grad_acc
        self._grad_acc = None
        assert grads is not None, "step() called with no accumulated gradients"

        overflow = False
        cur_scale = 1.0
        if self.loss_scaler is not None:
            cur_scale = self.loss_scaler.loss_scale
            if isinstance(grads, _StreamedGrads):
                # inf/nan in any leaf propagates into its squared norm.
                overflow = not bool(np.isfinite(np.float64(
                    np.asarray(jax.device_get(grads.sqnorms),
                               np.float64).sum())))
            else:
                overflow = bool(jax.device_get(jit_has_overflow(grads)))
            self.loss_scaler.update_scale(overflow)

        if overflow:
            self.skipped_steps += 1
            if isinstance(grads, _StreamedGrads) and \
                    self._offload is not None:
                # The streamed backward DONATED the device param buffers;
                # a skipped step never reaches _offload_step's re-upload,
                # so restore params from the host master here or the next
                # forward would feed deleted arrays into jit.
                self._offload_restore_params()
            log_dist("OVERFLOW! Skipping step. Attempted loss scale: {}, "
                     "reducing to {}".format(cur_scale,
                                             self.loss_scaler.loss_scale),
                     ranks=[0])
        else:
            group = self.optimizer.param_groups[0]
            beta1, beta2 = group.get("betas", (0.9, 0.999))
            if self._offload_mode():
                self._offload_step(grads, 1.0 / cur_scale, group["lr"])
            else:
                update_fn = self._get_update_fn()
                self.params, self.opt_state = update_fn(
                    self.params, self.opt_state, grads,
                    jnp.float32(1.0 / cur_scale),
                    jnp.float32(group["lr"]),
                    jnp.float32(beta1), jnp.float32(beta2))

            if self.lr_scheduler is not None:
                self.lr_scheduler.step(**(lr_kwargs or {}))
            report_progress = self.global_rank == 0
            if report_progress and \
                    (self.global_steps + 1) % self.steps_per_print() == 0:
                self._report_progress(self.global_steps + 1)

        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self._tensorboard_step_events()
        if hasattr(self.optimizer, "notify_step"):
            # OnebitAdam freeze bookkeeping (reference onebit_adam.py:369-372).
            # Keyed off applied updates (the jitted state['step']), not
            # global_steps, so fp16 overflow-skipped steps don't desync the
            # host flag from the compiled phase switch.
            was_frozen = getattr(self.optimizer, "adam_freeze_key", None)
            self.optimizer.notify_step(self.global_steps - self.skipped_steps)
            if was_frozen is not None and \
                    was_frozen != self.optimizer.adam_freeze_key:
                # The phase flag is traced into the compiled update program
                # on the shard_map path; drop the cache so the frozen phase
                # re-traces (the cond path is phase-agnostic but re-jitting
                # once is harmless).
                self._update_fn = None

    # ------------------------------------------------------- ZeRO-Offload tier

    def _init_offload(self):
        """Build the host-resident fp32 master + optimizer state.

        The reference keeps fp32 master partitions + Adam moments in pinned
        CPU memory and updates them with the AVX cpu_adam op
        (stage2.py:156,326-342, cpu_adam.cpp). Here: one contiguous fp32
        buffer per role (master/m/v) on the host; opt_state exposes per-leaf
        numpy *views* into those buffers so checkpoint save/load works
        unchanged; the C++ op updates the whole flat buffer in one
        OpenMP pass (no per-tensor launches — the multi_tensor_apply idea,
        done by layout instead of kernel machinery).
        """
        leaves, treedef = jax.tree_util.tree_flatten(self.params)
        shapes = [l.shape for l in leaves]
        sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]
        offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        total = int(offsets[-1])
        master = np.empty(total, np.float32)
        for leaf, off, size in zip(leaves, offsets[:-1], sizes):
            master[off:off + size] = np.asarray(
                jax.device_get(leaf), dtype=np.float32).ravel()
        m = np.zeros(total, np.float32)
        v = np.zeros(total, np.float32)

        def views(buf):
            return jax.tree_util.tree_unflatten(treedef, [
                buf[off:off + size].reshape(shape) for off, size, shape in
                zip(offsets[:-1], sizes, shapes)])

        self._offload = {
            "treedef": treedef, "shapes": shapes, "sizes": sizes,
            "offsets": offsets, "total": total,
            "master": master, "m": m, "v": v, "step": 0,
        }
        self.opt_state = {
            "step": np.int32(0),
            "exp_avg": views(m),
            "exp_avg_sq": views(v),
        }
        # The fp32 master now lives on host — device params drop to the
        # compute dtype (the reference keeps fp16 params on device + fp32
        # masters in pinned CPU memory, stage2.py:156,326-342). At 1.5B this
        # halves params+grads HBM from 12.4 GB to 6.2 GB.
        if self.compute_dtype != jnp.float32:
            cast = self._cast_to_compute
            self.params = cast(self.params)

    def _get_offload_pre_fn(self):
        """Jitted DEVICE-side unscale + global-norm clip, run BEFORE the
        host copy (the reference computes grad norms GPU-side pre-copy,
        stage2.py:818-840; doing it on host serialized the whole step)."""
        if self._offload_pre_fn is not None:
            return self._offload_pre_fn
        clip = self.gradient_clipping()

        def pre(grads, inv_scale):
            # Norms in f32, storage kept in the grad dtype, input buffers
            # donated: at 1.5B+ a full fp32 copy of the grads alongside the
            # bf16 originals would OOM a 16 GB chip.
            scale = inv_scale
            if clip > 0.0:
                norm = utils_global_norm(grads)
                scale = scale * jnp.minimum(
                    clip / (norm * inv_scale + 1e-6), 1.0)
            return jax.tree_util.tree_map(
                lambda x: (x.astype(jnp.float32) * scale).astype(x.dtype),
                grads)

        self._offload_pre_fn = jax.jit(pre, donate_argnums=0)
        return self._offload_pre_fn

    def _host_pack_lib(self):
        """The host flatten/unflatten op (csrc/utils, ≙ reference
        csrc/utils/flatten_unflatten.cpp used by engine/ZeRO bucketing):
        packs a chunk's grad leaves into the contiguous staging buffer
        with one OpenMP pass instead of a serial Python memcpy loop.
        Returns None when the op cannot build (numpy fallback)."""
        lib = getattr(self, "_host_pack_lib_cache", None)
        if lib is None and not getattr(self, "_host_pack_failed", False):
            try:
                from deepspeed_tpu.op_builder import UtilsBuilder
                lib = self._host_pack_lib_cache = UtilsBuilder().load()
            except Exception as e:
                self._host_pack_failed = True
                logger.info("utils op unavailable (%s); offload staging "
                            "uses the numpy pack loop", e)
        return lib

    def _offload_chunks(self):
        """Group flat-buffer leaf indices into ~16 MB transfer chunks for the
        copy/compute/copy pipeline."""
        target = 4 * 1024 * 1024  # fp32 elements (~16 MB)
        chunks, cur, cur_n = [], [], 0
        for i, size in enumerate(self._offload["sizes"]):
            cur.append(i)
            cur_n += size
            if cur_n >= target:
                chunks.append(cur)
                cur, cur_n = [], 0
        if cur:
            chunks.append(cur)
        return chunks

    def _offload_restore_params(self):
        """Re-materialize device params from the host fp32 master.

        Needed by the overflow-skip path under stream_gradients: the
        streamed backward donated the device param buffers, and a skipped
        step never reaches _offload_step's normal re-upload."""
        off = self._offload
        dtypes = [l.dtype for l in off["treedef"].flatten_up_to(self.params)]
        shard_leaves = off["treedef"].flatten_up_to(self.param_sharding) \
            if self._shardings_ready else [None] * len(off["sizes"])
        leaves = []
        for i in range(len(off["sizes"])):
            o, size = int(off["offsets"][i]), off["sizes"][i]
            host = off["master"][o:o + size].reshape(off["shapes"][i])
            arr = jnp.asarray(host, dtype=dtypes[i])
            if shard_leaves[i] is not None:
                arr = jax.device_put(arr, shard_leaves[i])
            leaves.append(arr)
        self.params = jax.tree_util.tree_unflatten(off["treedef"], leaves)

    def _offload_step(self, grads, inv_scale, lr):
        """Pipelined host optimizer step (reference's cpu-offload block,
        stage2.py:740-940 + DeepSpeedCPUAdam.step): grads are unscaled and
        clipped on device, streamed to host in chunks with
        copy_to_host_async, and the C++ OpenMP Adam runs on chunk i while
        chunk i+1 is still in flight and chunk i-1's updated params upload
        (async dispatch) — the double-buffering the reference builds with
        pinned memory + a migration stream (stage2.py:775-817)."""
        if self._offload is None:
            self._init_offload()
        off = self._offload
        opt = self.optimizer

        streamed = isinstance(grads, _StreamedGrads)
        if streamed:
            # Grads already live in off["stream_g"] (io_callback during
            # backward) — but only once the completion token resolves; the
            # callbacks are unordered and nothing else in the step depends
            # on them.
            jax.device_get(grads.token)
            # Unscale + global-norm clip become one host-side scale
            # factor, from the device-computed squared norms.
            clip = self.gradient_clipping()
            total_sq = float(np.asarray(jax.device_get(grads.sqnorms),
                                        np.float64).sum())
            host_scale = float(inv_scale)
            if clip > 0.0:
                norm = np.sqrt(total_sq) * float(inv_scale)
                host_scale *= min(clip / (norm + 1e-6), 1.0)
            g_leaves = None
        else:
            grads = self._get_offload_pre_fn()(grads, jnp.float32(inv_scale))
            g_leaves = off["treedef"].flatten_up_to(grads)
            del grads
            for leaf in g_leaves:
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()

        off["step"] += 1
        param_leaves = off["treedef"].flatten_up_to(self.params)
        dtypes = [l.dtype for l in param_leaves]
        shard_leaves = off["treedef"].flatten_up_to(self.param_sharding) \
            if self._shardings_ready else [None] * len(off["sizes"])
        new_leaves = [None] * len(param_leaves)
        # Release the old device params: the master (host) is authoritative,
        # and at 1.5B+ holding old params + grads + new params concurrently
        # would exceed a 16 GB chip. Leaves free as their refs drop. The
        # finally-block re-materializes params from the master even if a
        # chunk fails mid-loop — otherwise the next forward() would see
        # params=None and silently re-initialize fresh weights.
        self.params = None
        del param_leaves

        def upload(i):
            o, size = int(off["offsets"][i]), off["sizes"][i]
            host = off["master"][o:o + size].reshape(off["shapes"][i])
            arr = jnp.asarray(host, dtype=dtypes[i])
            if shard_leaves[i] is not None:
                arr = jax.device_put(arr, shard_leaves[i])
            return arr

        def stage(chunk):
            """Produce the chunk's contiguous fp32 grad view: streamed mode
            scales the already-host-resident span in place (overwritten
            next step); otherwise wait for the chunk's async device->host
            copies and pack them into one staging buffer."""
            t0 = time.time()
            lo = int(off["offsets"][chunk[0]])
            hi = int(off["offsets"][chunk[-1]] + off["sizes"][chunk[-1]])
            if streamed:
                host_g = off["stream_g"][lo:hi]
                if host_scale != 1.0:
                    np.multiply(host_g, host_scale, out=host_g)
                return host_g, lo, hi, time.time() - t0
            host_g = np.empty(hi - lo, np.float32)
            # D2H wait + fp32 cast per leaf first; the pack into the
            # contiguous staging buffer is then one OpenMP ds_flatten
            # call (chunk offsets are consecutive, so cumulative-size
            # packing lands each span at its flat-buffer offset).
            host_leaves = []
            for i in chunk:
                host_leaves.append(np.ascontiguousarray(np.asarray(
                    g_leaves[i], dtype=np.float32).ravel()))
                g_leaves[i] = None  # free this grad leaf's HBM now
            lib = self._host_pack_lib()
            if lib is not None:
                from deepspeed_tpu.op_builder import UtilsBuilder
                UtilsBuilder.flatten_into(lib, host_g, host_leaves)
            else:
                for t, i in zip(host_leaves, chunk):
                    o, size = int(off["offsets"][i]), off["sizes"][i]
                    host_g[o - lo:o - lo + size] = t
            return host_g, lo, hi, time.time() - t0

        # Double-buffered staging: a single worker thread stages chunk i+1
        # (copy-wait + memcpy pack, both GIL-releasing) while the C++ Adam
        # (ctypes call, GIL released) runs chunk i on the main thread.
        # Timing sums are kept per phase so the achieved overlap ratio
        # (serial sum / wall) is observable — the reference quantified its
        # fused copy the same way (ops/adam/cpu_adam.py:29-31).
        timing = {"stage_s": 0.0, "adam_s": 0.0, "upload_s": 0.0}
        t_wall = time.time()
        chunks = list(self._offload_chunks())
        pool = getattr(self, "_offload_pool", None)
        if pool is None:
            # One long-lived staging worker per engine — a per-step
            # executor would pay thread spawn/join every optimizer step.
            pool = self._offload_pool = ThreadPoolExecutor(max_workers=1)
        nxt = None
        try:
            nxt = pool.submit(stage, chunks[0]) if chunks else None
            for ci, chunk in enumerate(chunks):
                host_g, lo, hi, t_stage = nxt.result()
                timing["stage_s"] += t_stage
                nxt = pool.submit(stage, chunks[ci + 1]) \
                    if ci + 1 < len(chunks) else None
                step_kwargs = {"step": off["step"], "lr": lr}
                if getattr(opt, "supports_segments", False):
                    # LAMB trust ratios are per-tensor: each leaf in the
                    # chunk is its own span.
                    step_kwargs["segments"] = [
                        (int(off["offsets"][i]) - lo, off["sizes"][i])
                        for i in chunk]
                t0 = time.time()
                opt.step_flat(off["master"][lo:hi], host_g,
                              off["m"][lo:hi], off["v"][lo:hi],
                              **step_kwargs)
                timing["adam_s"] += time.time() - t0
                # Upload this chunk's updated params; device_put dispatches
                # asynchronously, overlapping the next chunk's host Adam.
                t0 = time.time()
                for i in chunk:
                    new_leaves[i] = upload(i)
                timing["upload_s"] += time.time() - t0
        finally:
            if nxt is not None:
                # Drain the in-flight staging future (it mutates g_leaves)
                # before tearing down state on an exception path.
                try:
                    nxt.result()
                except Exception:
                    pass
            del g_leaves
            self.params = jax.tree_util.tree_unflatten(
                off["treedef"],
                [leaf if leaf is not None else upload(i)
                 for i, leaf in enumerate(new_leaves)])
        timing["wall_s"] = time.time() - t_wall
        timing["chunks"] = len(chunks)
        timing["overlap_ratio"] = (
            (timing["stage_s"] + timing["adam_s"] + timing["upload_s"])
            / max(timing["wall_s"], 1e-9))
        self._offload_timing = timing
        self.opt_state["step"] = np.int32(off["step"])

    def step(self, lr_kwargs=None):
        """Weight update at gradient-accumulation boundaries
        (reference engine.py:989-1074)."""
        if self.wall_clock_breakdown():
            self.timers("step_microstep").start()
            self.timers("step").start()

        assert self.optimizer is not None, \
            "must provide optimizer during init in order to use step"

        if self.is_gradient_accumulation_boundary():
            if self.progressive_layer_drop:
                self.progressive_layer_drop.update_state(self.global_steps)
            self._take_model_step(lr_kwargs)

        self.tput_timer.stop(self.global_rank == 0)

        if self.wall_clock_breakdown():
            self.timers("step").stop()
            self.timers("step_microstep").stop()
            if self.is_gradient_accumulation_boundary() and \
                    self.global_steps % self.steps_per_print() == 0:
                self.timers.log([
                    "forward_microstep", "backward_microstep", "step_microstep"
                ], memory_breakdown=self.memory_breakdown())

        self.micro_steps += 1

    def _report_progress(self, step):
        """The ``steps_per_print`` line, fed from the telemetry registry:
        the same gauges Prometheus/TensorBoard export, so the printed
        step log and the scraped metrics can never disagree."""
        lr = self.get_lr() if self.optimizer else [0.0]
        mom = self.get_mom() if self.optimizer else [0.0]
        snap = self.telemetry.snapshot()
        log_dist(
            "step={}, skipped={}, lr={}, mom={}, samples={}, "
            "samples/sec={:.2f}".format(
                step, self.skipped_steps, lr, mom,
                int(snap.get("global_samples", 0)),
                snap.get("samples_per_sec", 0.0)), ranks=[0])

    # --------------------------------------------------------- fused fast path

    def _onebit_spmd_eligible(self):
        """True when train_batch should run the 1-bit Adam shard_map hot
        path: per-worker LOCAL gradients feed local momentum, and the
        compression-phase exchange is the genuinely compressed two-phase
        collective (uint8 n/8 + scales on the wire) instead of the dense
        GSPMD gradient average (reference: compression replaces the dense
        allreduce entirely, onebit_adam.py:369-372 + README '5x less
        communication'). Requires a pure-DP mesh: the reference's 1-bit
        Adam is likewise DP-only (no ZeRO composition)."""
        from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdam
        return (isinstance(self.optimizer, OnebitAdam)
                and mesh_lib.dp_size(self.mesh) > 1
                and mesh_lib.mp_size(self.mesh) <= 1
                and mesh_lib.pp_size(self.mesh) <= 1
                and mesh_lib.sp_size(self.mesh) <= 1
                and not self.zero_optimization()
                and not self.sparse_gradients_enabled())

    def _build_onebit_spmd_fused(self, frozen):
        """Fused fwd+bwd+1-bit-Adam step under shard_map over 'data'.

        Unlike the GSPMD fused path (XLA inserts a dense f32 gradient
        all-reduce), gradients here stay LOCAL to each worker: the warmup
        phase pmeans them explicitly (dense Adam semantics), and the
        frozen phase feeds them straight into local momentum, exchanging
        ONLY sign-packed momentum via compressed_allreduce — the wire
        payload is uint8 n/8 + one fp32 scale per phase. ``frozen`` is
        static (a collective cannot live inside lax.cond), so the step
        re-traces once at the freeze boundary; train_batch keys its cache
        on the phase."""
        from deepspeed_tpu.utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.runtime.fp16.onebit_adam import onebit_adam_update

        mesh = self.mesh
        axis = mesh_lib.DATA_AXIS
        dp = mesh_lib.dp_size(mesh)
        module = self.module
        cast = self._cast_to_compute
        clip = self.gradient_clipping()
        if frozen:
            self._warn_onebit_clip_once(clip)
        opt = self.optimizer
        group = opt.param_groups[0]
        eps = group["eps"]
        weight_decay = group["weight_decay"]
        freeze_step = opt.freeze_step
        tm = jax.tree_util.tree_map

        rep_spec = lambda tree: tm(lambda _: P(), tree)
        row_spec = lambda tree: tm(lambda _: P(axis), tree)
        state_spec = {
            "step": P(),
            "exp_avg": rep_spec(self.opt_state["exp_avg"]),
            "exp_avg_sq": rep_spec(self.opt_state["exp_avg_sq"]),
            "worker_error": row_spec(self.opt_state["worker_error"]),
            "server_error": row_spec(self.opt_state["server_error"]),
        }
        def spmd(params, opt_state, largs, rng, lr, beta1, beta2):
            def loss_fn(p):
                cp = cast(p)
                return module.apply({"params": cp}, *largs,
                                    rngs={"dropout": rng})

            loss, grads = jax.value_and_grad(loss_fn)(params)
            loss = jax.lax.pmean(loss, axis)
            grads = tm(lambda g: g.astype(jnp.float32), grads)
            if not frozen:
                # Warmup = dense Adam: average gradients explicitly (the
                # allreduce GSPMD would have inserted), then clip.
                grads = tm(lambda g: jax.lax.pmean(g, axis), grads)
                if clip > 0.0:
                    grads, _ = clip_grad_norm_(grads, clip)
            # Frozen phase: NO gradient averaging and no grad clipping —
            # local grads feed local momentum, the quantization scale
            # bounds the exchanged update (reference compression phase,
            # onebit_adam.py:319-355, operates unclipped on local grads).
            st = dict(opt_state)
            st["worker_error"] = tm(lambda e: e[0],
                                    opt_state["worker_error"])
            st["server_error"] = tm(lambda e: e[0],
                                    opt_state["server_error"])
            new_params, new_st = onebit_adam_update(
                params, grads, st, lr=lr, beta1=beta1, beta2=beta2,
                eps=eps, weight_decay=weight_decay,
                freeze_step=freeze_step, axis_name=axis, world_size=dp,
                frozen=frozen)
            new_st["worker_error"] = tm(lambda e: e[None],
                                        new_st["worker_error"])
            new_st["server_error"] = tm(lambda e: e[None],
                                        new_st["server_error"])
            return loss, new_params, new_st

        def fused(params, opt_state, args, rng, lr, beta1, beta2):
            in_specs = (rep_spec(params), state_spec,
                        tuple(mesh_lib.batch_partition_spec(x, dp)
                              for x in args), P(), P(), P(), P())
            out_specs = (P(), rep_spec(params), state_spec)
            return shard_map(spmd, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)(
                params, opt_state, args, rng, lr, beta1, beta2)

        out_shardings = None
        if self._shardings_ready:
            out_shardings = (None, self.param_sharding,
                             self.opt_state_sharding)
        return jax.jit(fused, donate_argnums=(0, 1),
                       out_shardings=out_shardings)

    def train_batch(self, batch=None, data_iter=None):
        """Fused fwd+bwd+update in ONE jitted XLA program (donated buffers).

        The perf path for gas==1, non-fp16 configs — XLA overlaps gradient
        collectives with backward compute the way the reference's
        overlap_comm/IPG machinery does by hand (stage2.py:283-287).
        Falls back to forward/backward/step when fp16 overflow bookkeeping or
        gradient accumulation requires host control.
        """
        if batch is None:
            assert data_iter is not None
            batch = next(data_iter)
        if self.fp16_enabled() or self.gradient_accumulation_steps() > 1 or \
                self._offload_mode():
            loss = self.forward(*batch) if isinstance(batch, (tuple, list)) \
                else self.forward(batch)
            self.backward(loss)
            self.step()
            return loss

        if isinstance(batch, (tuple, list)):
            inputs = tuple(jnp.asarray(x) if isinstance(x, np.ndarray) else x
                           for x in batch)
        else:
            inputs = (jnp.asarray(batch),)
        inputs = mesh_lib.shard_batch(self.mesh, inputs)

        if self.params is None:
            variables = self.module.init(
                {"params": self._next_rng(), "dropout": self._next_rng()},
                *inputs)
            self.params = variables["params"]
            self.opt_state = self.optimizer.init_state(self.params)
            self._setup_shardings()

        if self._onebit_spmd_eligible():
            # The 1-bit hot path keys on the phase: the compressed
            # collective cannot live under lax.cond, so freeze re-traces.
            key = ("onebit", len(inputs),
                   bool(self.optimizer.adam_freeze_key))
            if key not in self._fused_step_cache:
                self._fused_step_cache[key] = self._build_onebit_spmd_fused(
                    frozen=key[2])
        else:
            key = len(inputs)
        if key not in self._fused_step_cache:
            module = self.module
            cast = self._cast_to_compute
            clip = self.gradient_clipping()
            optimizer = self.optimizer
            grad_constraint = self._grad_constraint

            def fused(params, opt_state, args, rng, lr, beta1, beta2):
                def loss_fn(p):
                    cp = cast(p)
                    return module.apply({"params": cp}, *args,
                                        rngs={"dropout": rng})

                loss, grads = jax.value_and_grad(loss_fn)(params)
                if grad_constraint is not None:
                    grads = jax.lax.with_sharding_constraint(
                        grads, grad_constraint)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads)
                if clip > 0.0:
                    grads, _ = clip_grad_norm_(grads, clip)
                new_params, new_state = optimizer.update(
                    params, grads, opt_state, lr=lr, betas=(beta1, beta2))
                return loss, new_params, new_state

            out_shardings = None
            if self._shardings_ready:
                out_shardings = (None, self.param_sharding,
                                 self.opt_state_sharding)
            self._fused_step_cache[key] = jax.jit(
                fused, donate_argnums=(0, 1), out_shardings=out_shardings)

        self.tput_timer.start()
        group = self.optimizer.param_groups[0]
        beta1, beta2 = group.get("betas", (0.9, 0.999))
        jitted = self._fused_step_cache[key]
        rng = self._next_rng()
        lr_d = jnp.float32(group["lr"])
        b1_d, b2_d = jnp.float32(beta1), jnp.float32(beta2)
        # Shapes-only xray capture of the exact fused program about to
        # run (params/opt_state are donated — the stash abstracts
        # leaves immediately and retains no buffer).
        self.xray.stash("fused_train_step[{}]".format(key), jitted,
                        self.params, self.opt_state, inputs, rng,
                        lr_d, b1_d, b2_d,
                        donate=("params", "opt_state"))
        self.xray.note("fused_train_step[{}]".format(key),
                       tokens=self.train_batch_size())
        loss, self.params, self.opt_state = jitted(
            self.params, self.opt_state, inputs, rng, lr_d, b1_d, b2_d)
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self.micro_steps += 1
        self._last_loss = loss
        self._tensorboard_step_events()
        if hasattr(self.optimizer, "notify_step"):
            self.optimizer.notify_step(self.global_steps - self.skipped_steps)
        self.tput_timer.stop(True)
        return loss

    # -------------------------------------------------------- flops profiler

    def flops_profiler_enabled(self):
        return self._config.flops_profiler_config.enabled

    def flops_profiler_start_step(self):
        return self._config.flops_profiler_config.start_step

    def flops_profiler_end_step(self):
        return self._config.flops_profiler_config.end_step

    def _start_flops_profiler(self):
        from deepspeed_tpu.profiling.flops_profiler.profiler import FlopsProfiler
        # Share this engine's observatory: profiled programs and the
        # fused-step stash land in ONE record set (and one AOT-analysis
        # cache), so perf_xray() and the profiler report agree.
        self.flops_profiler = FlopsProfiler(self.module, xray=self.xray)
        self.flops_profiler.start_profile()

    def _stop_flops_profiler(self):
        if hasattr(self, "flops_profiler"):
            self.flops_profiler.stop_profile()
            self.flops_profiler.print_model_profile(
                top_modules=self._config.flops_profiler_config.top_modules)
            self.flops_profiler.end_profile()

    def perf_xray(self):
        """The schema-versioned ``perf_xray`` section for the training
        side: every fused step program this engine compiled, with HLO
        fingerprint, cost-model flops/bytes, and the peak-HBM split.
        First call pays the one-time AOT analysis (off the step path)."""
        return self.xray.to_json()

    # ------------------------------------------------------------- checkpoint

    def _get_ckpt_name(self, checkpoints_path, tag):
        mp_rank = 0 if self.mpu is None else self.mpu.get_model_parallel_rank()
        return os.path.join(checkpoints_path, str(tag),
                            "mp_rank_{:02d}_model_states.pt".format(mp_rank))

    def _get_zero_ckpt_name(self, checkpoints_path, tag, dp_rank=0):
        mp_rank = 0 if self.mpu is None else self.mpu.get_model_parallel_rank()
        zero_ckpt_name = os.path.join(
            checkpoints_path, str(tag),
            "zero_pp_rank_{}_mp_rank_{:02d}optim_states.pt".format(
                dp_rank, mp_rank))
        return zero_ckpt_name

    def _to_host(self, tree):
        return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)),
                                      tree)

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        """Save the checkpoint set (reference engine.py:1461-1561): model
        states per mp-rank, zero optim states per (dp,mp) rank, 'latest' tag
        file. Serialization is numpy+pickle instead of torch.save."""
        if tag is None:
            tag = "global_step{}".format(self.global_steps)
        self._checkpoint_tag_validation(tag)

        save_path = self._get_ckpt_name(save_dir, tag)
        ensure_directory_exists(save_path)

        state = {
            "module": self._to_host(self.params),
            "optimizer": None if self.zero_optimization() else
            self._optimizer_state_for_save(),
            "lr_scheduler": self.lr_scheduler.state_dict()
            if self.lr_scheduler is not None else None,
            "csr_tensor_module_names": [],
            "skipped_steps": self.skipped_steps,
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "dp_world_size": self.dp_world_size,
            "mp_world_size": self.mp_world_size,
            "loss_scaler": self.loss_scaler.__dict__.copy()
            if self.loss_scaler is not None else None,
        }
        if client_state is not None:
            state.update(client_state)
        with open(save_path, "wb") as f:
            pickle.dump(state, f)
        logger.info("Saving model checkpoint: {}".format(save_path))

        if self.zero_optimization():
            self._save_zero_checkpoint(save_dir, tag)

        if save_latest:
            with open(os.path.join(save_dir, "latest"), "w") as fd:
                fd.write(tag)
        return True

    def _save_zero_checkpoint(self, save_dir, tag):
        """Write the zero optim-state files. With elastic_checkpoint (the
        default, reference zero/config.py:25), state is split into one
        world-size-agnostic shard file per dp rank (reference
        stage1.py:848-1078's elastic format): a later load at a DIFFERENT dp
        world size reassembles the full logical state from however many shard
        files exist and re-partitions onto the current mesh."""
        opt_sd = self._optimizer_state_for_save()
        elastic = self.zero_elastic_checkpoint() and not self._offload_mode()
        dp_world = mesh_lib.dp_size(self.mesh)
        if not elastic or dp_world <= 1:
            zero_path = self._get_zero_ckpt_name(save_dir, tag)
            ensure_directory_exists(zero_path)
            with open(zero_path, "wb") as f:
                pickle.dump({"optimizer_state_dict": opt_sd}, f)
            return
        state_host = opt_sd.pop("state")
        for r in range(dp_world):
            zero_path = self._get_zero_ckpt_name(save_dir, tag, dp_rank=r)
            ensure_directory_exists(zero_path)
            with open(zero_path, "wb") as f:
                pickle.dump({
                    "optimizer_state_dict": opt_sd,
                    "state_shards": self._partition_state_for_rank(
                        state_host, r, dp_world),
                    "zero_dp_world_size": dp_world,
                }, f)

    def _partition_state_for_rank(self, state_host, dp_rank, dp_world):
        """Shard one dp rank's slice of host optimizer state. Each leaf
        becomes ('shard', dim, slice) along its data-sharded dim, or
        ('full', array) in rank 0's file only (replicated/indivisible
        leaves — e.g. the scalar step, small biases)."""
        def slice_leaf(leaf):
            arr = np.asarray(leaf)
            spec = mesh_lib._leaf_spec_over_axis(arr, mesh_lib.DATA_AXIS,
                                                 dp_world)
            dim = next((i for i, ax in enumerate(spec)
                        if ax == mesh_lib.DATA_AXIS), None)
            if dim is None:
                return ("full", arr) if dp_rank == 0 else ("ref",)
            per = arr.shape[dim] // dp_world
            idx = [slice(None)] * arr.ndim
            idx[dim] = slice(dp_rank * per, (dp_rank + 1) * per)
            return ("shard", dim, arr[tuple(idx)])

        return jax.tree_util.tree_map(slice_leaf, state_host)

    @staticmethod
    def _merge_state_shards(shard_trees):
        """Inverse of _partition_state_for_rank: reassemble the full logical
        state from every saved dp rank's shard tree."""
        def merge(*entries):
            first = entries[0]
            if first[0] == "full" or first[0] == "ref":
                full = next(e for e in entries if e[0] == "full")
                return full[1]
            dim = first[1]
            return np.concatenate([e[2] for e in entries], axis=dim)

        return jax.tree_util.tree_map(
            merge, *shard_trees,
            is_leaf=lambda x: isinstance(x, tuple) and len(x) > 0 and
            x[0] in ("full", "ref", "shard"))

    def _optimizer_state_for_save(self):
        sd = {"state": self._to_host(self.opt_state)
              if self.opt_state is not None else None}
        if self._offload_mode() and self._offload is not None:
            # Persist the host fp32 master weights: resume must keep full
            # master precision (reference saves
            # single_partition_of_fp32_groups, stage2.py:1704); rebuilding
            # from bf16 params would drift the training trajectory.
            sd["fp32_master"] = self._offload["master"].copy()
        if hasattr(self.optimizer, "state_dict"):
            sd.update(self.optimizer.state_dict())
        return sd

    def _load_zero_state(self, load_dir, tag):
        """Read zero optim-state file(s). Elastic layout: every saved dp
        rank's shard file is read and the full logical state reassembled, so
        loading at a different dp world size than the save re-partitions
        naturally (reference engine.py:1376-1442 + stage1.py:946-1023)."""
        zero_path = self._get_zero_ckpt_name(load_dir, tag, dp_rank=0)
        if not os.path.exists(zero_path):
            return None
        with open(zero_path, "rb") as f:
            head = pickle.load(f)
        if "state_shards" not in head:
            return head["optimizer_state_dict"]  # non-elastic single file
        saved_world = head["zero_dp_world_size"]
        shard_trees = [head["state_shards"]]
        for r in range(1, saved_world):
            path_r = self._get_zero_ckpt_name(load_dir, tag, dp_rank=r)
            assert os.path.exists(path_r), (
                "elastic zero checkpoint saved at dp={} is missing shard "
                "file {}".format(saved_world, path_r))
            with open(path_r, "rb") as f:
                shard_trees.append(pickle.load(f)["state_shards"])
        opt_sd = dict(head["optimizer_state_dict"])
        opt_sd["state"] = self._merge_state_shards(shard_trees)
        if saved_world != mesh_lib.dp_size(self.mesh):
            log_dist("elastic zero checkpoint: re-partitioning optimizer "
                     "state saved at dp={} onto dp={}".format(
                         saved_world, mesh_lib.dp_size(self.mesh)), ranks=[0])
        return opt_sd

    def _checkpoint_tag_validation(self, tag):
        """Cross-rank tag consistency (reference engine.py:1444-1459): every
        process sha1-hashes the tag, hashes are all-gathered over processes,
        and a mismatch warns or fails per checkpoint_tag_validation_fail. In
        a single-process (single-controller) run the gather is trivial."""
        if not self.checkpoint_tag_validation_enabled():
            return
        tag_hash = hashlib.sha1(str(tag).encode()).hexdigest()
        local = np.frombuffer(bytes.fromhex(tag_hash), np.uint8)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            gathered = np.asarray(
                multihost_utils.process_allgather(local))
            valid = bool((gathered == gathered[0]).all())
        else:
            valid = True
        if not valid:
            msg = "checkpoint tag '{}' inconsistent across ranks: not all " \
                  "processes computed the same tag hash".format(tag)
            if self.checkpoint_tag_validation_fail():
                raise RuntimeError(msg)
            logger.warning(msg)
        return tag_hash

    def load_checkpoint(self,
                        load_dir,
                        tag=None,
                        load_module_strict=True,
                        load_optimizer_states=True,
                        load_lr_scheduler_states=True):
        """Load checkpoint (reference engine.py:1271-1374). Returns
        (load_path, client_state)."""
        if tag is None:
            latest_path = os.path.join(load_dir, "latest")
            if os.path.isfile(latest_path):
                with open(latest_path, "r") as fd:
                    tag = fd.read().strip()
            else:
                logger.warning(
                    "Unable to find latest file at {}, if trying to load "
                    "latest checkpoint please pass an explicit tag".format(
                        latest_path))
                return None, None

        load_path = self._get_ckpt_name(load_dir, tag)
        if not os.path.exists(load_path):
            logger.warning(
                "Client provided checkpoint load path: {} does not exist ... "
                "attempting to load from zero shards".format(load_path))
            return None, None

        with open(load_path, "rb") as f:
            checkpoint = pickle.load(f)

        self.params = jax.tree_util.tree_map(jnp.asarray, checkpoint["module"])
        if self.optimizer is not None and self.opt_state is None and \
                not self._offload_mode():
            self.opt_state = self.optimizer.init_state(self.params)
        self._setup_shardings()
        if self._offload_mode():
            self._init_offload()

        if load_optimizer_states:
            opt_sd = None
            if self.zero_optimization():
                opt_sd = self._load_zero_state(load_dir, tag)
            else:
                opt_sd = checkpoint.get("optimizer")
            if opt_sd is not None and opt_sd.get("state") is not None:
                if self._offload_mode():
                    # Copy saved moments into the host buffers (views).
                    saved = opt_sd["state"]
                    off = self._offload
                    for buf, key in ((off["m"], "exp_avg"),
                                     (off["v"], "exp_avg_sq")):
                        leaves = off["treedef"].flatten_up_to(saved[key])
                        for leaf, o, size in zip(leaves, off["offsets"][:-1],
                                                 off["sizes"]):
                            buf[o:o + size] = np.asarray(leaf,
                                                         np.float32).ravel()
                    if opt_sd.get("fp32_master") is not None:
                        # Full-precision master resume (reference
                        # load_from_fp32_weights, stage2.py:1718-1741): the
                        # saved fp32 buffer is authoritative, not the bf16
                        # module params _init_offload rebuilt it from.
                        off["master"][:] = opt_sd["fp32_master"]
                    off["step"] = int(saved["step"])
                    self.opt_state["step"] = np.int32(off["step"])
                else:
                    self.opt_state = jax.tree_util.tree_map(
                        jnp.asarray, opt_sd["state"])
                    self.opt_state = jax.device_put(self.opt_state,
                                                    self.opt_state_sharding)
                if hasattr(self.optimizer, "load_state_dict"):
                    self.optimizer.load_state_dict(opt_sd)

        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                checkpoint.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(checkpoint["lr_scheduler"])

        if self.loss_scaler is not None and checkpoint.get("loss_scaler"):
            self.loss_scaler.__dict__.update(checkpoint["loss_scaler"])

        self.global_steps = checkpoint.get("global_steps", 0)
        self.global_samples = checkpoint.get(
            "global_samples", self.global_steps * self.train_batch_size())
        self.skipped_steps = checkpoint.get("skipped_steps", 0)
        self.micro_steps = self.global_steps * self.gradient_accumulation_steps()
        if hasattr(self.optimizer, "notify_step"):
            # Resync host-side freeze bookkeeping with the restored
            # counters: a resume past freeze_step must select the frozen
            # (compressed) program for its FIRST step, not run one
            # warmup-phase step until notify_step flips the flag post-step.
            self.optimizer.notify_step(self.global_steps - self.skipped_steps)

        deepspeed_states = [
            "module", "optimizer", "lr_scheduler", "csr_tensor_module_names",
            "skipped_steps", "global_steps", "global_samples",
            "dp_world_size", "mp_world_size", "loss_scaler",
        ]
        client_state = {k: v for k, v in checkpoint.items()
                        if k not in deepspeed_states}
        return load_path, client_state

    # -------------------------------------------------------------- misc state

    def _dump_state(self):
        self._config.print("DeepSpeedEngine configuration")

    @property
    def ds_config(self):
        return self._config
