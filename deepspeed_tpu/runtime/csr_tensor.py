"""Compressed sparse-row tensor for sparse (embedding) gradients.

Behavior-parity port of reference runtime/csr_tensor.py:11-59. On TPU the
index/value pair is carried as jnp arrays; the sparse all-reduce is an
all-gather of (indices, values) over the data axis (engine.csr_allreduce),
mirroring the reference's dim-padded allgather strategy.
"""

import jax.numpy as jnp


class CSRTensor(object):
    """Compressed Sparse Row format: row indices + dense value rows."""

    def __init__(self, dense_tensor=None, indices=None, values=None, dense_size=None):
        self.orig_dense_tensor = dense_tensor
        if dense_tensor is not None:
            # Rows with any non-zero entry are kept (embedding-grad style
            # sparsity: most rows untouched by a batch are all-zero).
            row_mask = jnp.any(dense_tensor != 0, axis=tuple(range(1, dense_tensor.ndim)))
            idx = jnp.nonzero(row_mask)[0]
            self.indices = idx
            self.values = dense_tensor[idx]
            self.dense_size = tuple(dense_tensor.shape)
        else:
            self.indices = indices
            self.values = values
            self.dense_size = tuple(dense_size) if dense_size is not None else None

    @staticmethod
    def type():
        return "deepspeed_tpu.CSRTensor"

    def to_dense(self):
        dense = jnp.zeros(self.dense_size, dtype=self.values.dtype)
        return dense.at[self.indices].add(self.values)

    def sparse_size(self):
        index_size = self.indices.shape[0]
        row_size = 1
        for d in self.dense_size[1:]:
            row_size *= d
        sparse_size = index_size + index_size * row_size
        dense_size = 1
        for d in self.dense_size:
            dense_size *= d
        return sparse_size, dense_size

    def add(self, b):
        assert self.dense_size == b.dense_size
        self.indices = jnp.concatenate([self.indices, b.indices])
        self.values = jnp.concatenate([self.values, b.values])

    def __str__(self):
        sparse_size, dense_size = self.sparse_size()
        return ("DeepSpeed.CSRTensor(indices_size={}, values_size={}, "
                "dense_size={}, device=TPU, reduction_factor={:.2f})".format(
                    self.indices.shape, self.values.shape, self.dense_size,
                    dense_size / max(sparse_size, 1)))

    def __repr__(self):
        return self.__str__()


def pad_csr(indices, values, target_rows):
    """Pad a CSR pair to a fixed row count for collective exchange.

    Padding rows point at index 0 with all-zero values, so scatter-add in
    ``to_dense`` is unaffected (the reference's dim-padded allgather,
    engine.py:1186-1242, pads the same way before exchanging).
    """
    import jax.numpy as jnp
    k = indices.shape[0]
    if k > target_rows:
        raise ValueError(
            "pad_csr: {} nonzero rows exceed the exchange budget of {} — "
            "raise target_rows or gradients would be silently dropped"
            .format(k, target_rows))
    if k == target_rows:
        return indices, values
    pad_n = target_rows - k
    idx = jnp.concatenate([indices, jnp.zeros((pad_n,), indices.dtype)])
    val = jnp.concatenate(
        [values, jnp.zeros((pad_n,) + values.shape[1:], values.dtype)])
    return idx, val


def sparse_grad_exchange(grad, axis_name, k, average=True):
    """Cross-device reduction of a row-sparse dense gradient (an embedding
    table's grad: at most one touched row per input token) by exchanging
    (row-index, row-value) pairs instead of dense-allreducing the full
    [vocab, dim] table — the TPU-native form of the reference's CSR
    allreduce (engine.py:1186-1242). Runs inside shard_map.

    ``k`` bounds the nonzero rows per device (the local token count, static
    at trace time). Comm volume is W*k*(dim+1) vs vocab*dim for dense.
    Row extraction uses top_k on the nonzero-row mask: padding slots point at
    all-zero rows, so the final scatter-add is unaffected.
    """
    import jax

    vocab = grad.shape[0]
    k = min(int(k), vocab)
    if k == vocab:
        # Budget covers the whole table: plain dense reduction is cheaper.
        out = jax.lax.psum(grad, axis_name)
        return out / jax.lax.psum(1, axis_name) if average else out
    row_mask = jnp.any(grad != 0, axis=tuple(range(1, grad.ndim)))
    # Tied-softmax guard: when the table doubles as the output head, the
    # softmax makes EVERY row's grad nonzero and a k-row exchange would
    # silently drop gradient. The overflow flag is psum'd so every device
    # takes the same cond branch (collectives inside cond must not diverge).
    dense_needed = jax.lax.psum(
        (jnp.sum(row_mask.astype(jnp.int32)) > k).astype(jnp.int32),
        axis_name) > 0
    w = jax.lax.psum(1, axis_name)

    def dense_path(g):
        out = jax.lax.psum(g, axis_name)
        return out / w if average else out

    def sparse_path(g):
        _, idx = jax.lax.top_k(row_mask.astype(jnp.int32), k)
        vals = g[idx]
        idx_g, val_g = csr_allreduce(idx, vals, axis_name, average=average)
        return jnp.zeros_like(g).at[idx_g].add(val_g)

    return jax.lax.cond(dense_needed, dense_path, sparse_path, grad)


def csr_allreduce(indices, values, axis_name, average=True):
    """Sparse gradient allreduce over a mesh axis: all_gather the (padded)
    index/value pairs instead of dense-allreducing the full embedding table
    (reference csr_allreduce_no_retain → engine.py:1186-1242).

    Use inside shard_map; every rank must pass equal shapes (pad_csr).
    Returns the merged (indices, values) with duplicates left in place —
    CSRTensor.to_dense scatter-*adds*, which sums contributions.
    """
    import jax
    w = jax.lax.psum(1, axis_name)
    idx_g = jax.lax.all_gather(indices, axis_name)      # [W, k]
    val_g = jax.lax.all_gather(values, axis_name)       # [W, k, ...]
    if average:
        val_g = val_g / w
    return (idx_g.reshape((-1,)),
            val_g.reshape((-1,) + val_g.shape[2:]))
