"""Progressive Layer Drop schedule (reference runtime/progressive_layer_drop.py:5-33).

theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar. The engine injects
``progressive_layer_drop=True, pld_theta=get_theta()`` kwargs into each forward
(engine.py:815-816) and advances the state at every model step (:1003-1004).
"""

import numpy as np

from deepspeed_tpu.utils.logging import log_dist


class ProgressiveLayerDrop(object):
    def __init__(self, theta=0.5, gamma=0.001):
        super().__init__()
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist("Enabled progressive layer dropping (theta = {})".format(theta),
                 ranks=[0])

    def get_state(self):
        kwargs = {"progressive_layer_drop": True, "pld_theta": self.get_theta()}
        return kwargs

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        def _prob(x, gamma, p):
            return (1.0 - p) * np.exp(-gamma * x) + p

        self.current_theta = _prob(global_step, self.gamma, self.theta)
