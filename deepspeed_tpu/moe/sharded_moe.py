"""Top-k gating + capacity-based dispatch for mixture-of-experts.

TPU-native formulation of the DeepSpeed-MoE gating tier (the reference
repo gained `deepspeed/moe/sharded_moe.py` with top1gating/top2gating in
later releases; v0.3.10 predates it — like sequence parallelism, this is
a beyond-reference capability, SURVEY §0). The math follows the GShard
recipe: per-token softmax gate, capacity = ceil(k*S/E * factor), dispatch
and combine expressed as EINSUMS over a [tokens, experts, capacity]
tensor.

Einsums are the whole point on TPU: with tokens sharded over 'data' and
the expert dim sharded over 'model' (expert parallelism), XLA's SPMD
partitioner lowers `dispatch @ tokens` / `combine @ expert_out` into the
token all-to-alls automatically — no hand-written NCCL a2a plumbing like
a CUDA implementation needs, and the collectives fuse into the
surrounding program.

Everything is fixed-shape (capacity pads/drops) so one compiled program
serves every step — data-dependent token routing becomes dense masked
arithmetic, which is what the MXU wants anyway.
"""

import jax
import jax.numpy as jnp


def _one_hot(x, n):
    # Positions arrive as float cumsum products — cast for one_hot.
    return jax.nn.one_hot(jnp.asarray(x).astype(jnp.int32), n,
                          dtype=jnp.float32)


def _capacity(tokens, num_experts, k, factor, min_capacity):
    cap = int(max(min_capacity, -(-(k * tokens * factor) // num_experts)))
    return min(cap, tokens)


def _load_balance_loss(gates, mask1):
    """GShard aux loss: E * <fraction of tokens per expert> . <mean gate
    per expert>; minimized when routing is uniform."""
    e = gates.shape[-1]
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    return e * jnp.sum(me * ce)


def top1gating(logits, capacity_factor=1.0, min_capacity=4,
               noise_rng=None, noise_eps=1e-2, used_token_mask=None):
    """Switch-style top-1 gating.

    Args:
      logits: [S, E] fp32 router outputs.
      noise_rng: optional PRNGKey — multiplicative jitter on the routing
        logits (the 'Jitter' policy), training-time exploration.
      used_token_mask: optional [S] 0/1 — padding tokens get no slot.
    Returns: (l_aux, combine [S, E, C] fp32, dispatch [S, E, C] bool,
      exp_counts [E]).
    """
    s, e = logits.shape
    cap = _capacity(s, e, 1, capacity_factor, min_capacity)
    route_logits = logits
    if noise_rng is not None:
        route_logits = logits * jax.random.uniform(
            noise_rng, logits.shape, minval=1.0 - noise_eps,
            maxval=1.0 + noise_eps)
    gates = jax.nn.softmax(logits, axis=-1)               # [S, E]
    expert1 = jnp.argmax(route_logits, axis=-1)           # [S]
    mask1 = _one_hot(expert1, e)                          # [S, E]
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[:, None]
    l_aux = _load_balance_loss(gates, mask1)
    # Position of each token in its expert's buffer; capacity overflow
    # drops the token (its combine weights become 0 — residual carries it).
    pos1 = jnp.cumsum(mask1, axis=0) - mask1              # [S, E]
    mask1 = mask1 * (pos1 < cap)
    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)
    gate1 = jnp.sum(gates * mask1, axis=-1)               # [S]
    pos_in_exp = jnp.sum(pos1 * mask1, axis=-1)           # [S]
    dispatch = (mask1[:, :, None] *
                _one_hot(pos_in_exp, cap)[:, None, :])    # [S, E, C]
    combine = gate1[:, None, None] * dispatch
    return l_aux, combine, dispatch.astype(bool), exp_counts


def top2gating(logits, capacity_factor=1.0, min_capacity=4,
               noise_rng=None, used_token_mask=None):
    """GShard top-2 gating: second expert sampled from the residual
    distribution, weights renormalized over the two winners."""
    s, e = logits.shape
    cap = _capacity(s, e, 2, capacity_factor, min_capacity)
    gates = jax.nn.softmax(logits, axis=-1)
    expert1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(expert1, e)
    logits2 = jnp.where(mask1 > 0, -jnp.inf, logits)
    if noise_rng is not None:
        # GShard samples the 2nd expert proportionally to its gate.
        logits2 = logits2 + jax.random.gumbel(noise_rng, logits2.shape)
    expert2 = jnp.argmax(logits2, axis=-1)
    mask2 = _one_hot(expert2, e)
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[:, None]
        mask2 = mask2 * used_token_mask[:, None]
    l_aux = _load_balance_loss(gates, mask1)

    pos1 = jnp.cumsum(mask1, axis=0) - mask1
    # Expert-2 slots start after all expert-1 claims on the same expert.
    pos2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0)
    mask1 = mask1 * (pos1 < cap)
    mask2 = mask2 * (pos2 < cap)
    exp_counts = jnp.sum(mask1 + mask2, axis=0).astype(jnp.int32)

    gate1 = jnp.sum(gates * mask1, axis=-1)
    gate2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.maximum(gate1 + gate2, 1e-9)
    gate1, gate2 = gate1 / denom, gate2 / denom

    p1 = jnp.sum(pos1 * mask1, axis=-1)
    p2 = jnp.sum(pos2 * mask2, axis=-1)
    disp1 = mask1[:, :, None] * _one_hot(p1, cap)[:, None, :]
    disp2 = mask2[:, :, None] * _one_hot(p2, cap)[:, None, :]
    combine = gate1[:, None, None] * disp1 + gate2[:, None, None] * disp2
    dispatch = (disp1 + disp2) > 0
    return l_aux, combine, dispatch, exp_counts
