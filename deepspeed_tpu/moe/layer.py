"""Mixture-of-Experts layer with expert parallelism.

`MoE` mirrors the DeepSpeed-MoE user surface (later-release
`deepspeed/moe/layer.py`: construct with a sub-`expert` module, call on
[B, T, C] hidden states, get `(output, l_aux, exp_counts)` back) on a
TPU-native implementation:

- experts are ONE stacked parameter tree with a leading [num_experts]
  axis (`nn.vmap` over the expert module) — a single pytree leaf per
  weight, so ZeRO/optimizer/checkpoint plumbing needs no special cases;
- EXPERT PARALLELISM is a sharding rule, not a process group: the expert
  axis shards over the mesh's 'model' axis
  (parallel/mesh.py DEFAULT_TP_RULES), and the dispatch/combine einsums
  (sharded_moe.py) let XLA insert the token all-to-alls — the CUDA
  implementation's explicit expert-parallel comm groups and a2a calls
  have no analogue here because GSPMD derives them;
- routing is fixed-shape capacity-based dense math (MXU-friendly), so
  the layer jits once.
"""

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from deepspeed_tpu.moe.sharded_moe import top1gating, top2gating


class Experts(nn.Module):
    """num_experts stacked copies of the expert module: parameters get a
    leading expert axis (the axis expert parallelism shards)."""

    expert: Callable[[], nn.Module]
    num_experts: int

    @nn.compact
    def __call__(self, x):
        # x: [E, cap, C] — one row of tokens per expert.
        vmapped = nn.vmap(
            lambda mdl, xi: mdl(xi),
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=0, out_axes=0,
            axis_size=self.num_experts)
        return vmapped(self.expert(), x)


class MoE(nn.Module):
    """Sparsely-gated mixture-of-experts block.

    Args mirror the DeepSpeed MoE constructor: ``hidden_size``,
    ``expert`` (a zero-arg factory returning the expert flax module, e.g.
    ``lambda: MLP(cfg)``), ``num_experts``, ``k`` (1 or 2),
    ``capacity_factor`` / ``eval_capacity_factor``, ``min_capacity``,
    ``noisy_gate_policy`` (None or 'Jitter').

    Call: ``out, l_aux, exp_counts = moe(x, deterministic=...)`` with x
    [B, T, C]. Add ``l_aux`` (scaled by your aux coefficient) to the
    training loss; dropped-by-capacity tokens ride the residual (output
    contribution 0).

    ``deterministic`` defaults to None = infer from the rng plumbing: the
    engine threads a 'dropout' rng stream into training applies only, so
    a nested MoE inside a model that does not forward the kwarg still
    trains with ``capacity_factor`` (and Jitter noise) rather than
    silently using the eval settings.
    """

    hidden_size: int
    expert: Callable[[], nn.Module]
    num_experts: int = 1
    k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Any = None

    @nn.compact
    def __call__(self, x, deterministic=None):
        if deterministic is None:
            deterministic = not self.has_rng("dropout")
        b, t, c = x.shape
        s = b * t
        tokens = x.reshape(s, c)
        # Router in fp32 — tiny matmul, and gate probabilities/cumsum
        # positions are precision-sensitive.
        logits = nn.Dense(self.num_experts, use_bias=False,
                          dtype=jnp.float32, name="gate")(
                              tokens.astype(jnp.float32))
        noise_rng = None
        if self.noisy_gate_policy == "Jitter" and not deterministic:
            noise_rng = self.make_rng("dropout")
        factor = self.capacity_factor if not deterministic \
            else self.eval_capacity_factor
        gate = top1gating if self.k == 1 else top2gating
        l_aux, combine, dispatch, exp_counts = gate(
            logits, capacity_factor=factor, min_capacity=self.min_capacity,
            noise_rng=noise_rng)
        # [S, E, C] x [S, C'] -> [E, cap, C']: the expert-parallel
        # all-to-all, derived by GSPMD from the shardings.
        dispatched = jnp.einsum(
            "sec,sm->ecm", dispatch.astype(x.dtype), tokens)
        expert_out = Experts(self.expert, self.num_experts,
                             name="experts")(dispatched)
        out = jnp.einsum("sec,ecm->sm", combine.astype(x.dtype),
                         expert_out.astype(x.dtype))
        return (out.reshape(b, t, -1), l_aux,
                exp_counts)
