"""MoE utilities: identify expert parameters (for per-group optimizer
settings / checkpoint policies) — mirrors the DeepSpeed helper surface
(later-release deepspeed/moe/utils.py is_moe_param /
split_params_into_different_moe_groups_for_optimizer)."""

import jax


def is_moe_param_path(path) -> bool:
    """True when a flax param tree path belongs to a stacked expert
    (leading expert axis, sharded by expert parallelism)."""
    names = [str(getattr(p, "key", getattr(p, "name", p))) for p in path]
    return "experts" in names


def split_moe_param_groups(params):
    """Partition a param pytree into (dense_tree, expert_tree) with None
    holes, so callers can apply different optimizer settings (the
    reference splits torch param groups; functionally-partitioned pytrees
    are the JAX equivalent)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    dense = [None if is_moe_param_path(p) else l for p, l in flat]
    expert = [l if is_moe_param_path(p) else None for p, l in flat]
    return (jax.tree_util.tree_unflatten(treedef, dense),
            jax.tree_util.tree_unflatten(treedef, expert))
