from deepspeed_tpu.moe.layer import Experts, MoE
from deepspeed_tpu.moe.sharded_moe import top1gating, top2gating
from deepspeed_tpu.moe.utils import is_moe_param_path, split_moe_param_groups

__all__ = ["MoE", "Experts", "top1gating", "top2gating",
           "is_moe_param_path", "split_moe_param_groups"]
