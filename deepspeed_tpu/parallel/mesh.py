"""Device mesh + sharding construction — the TPU-native process-group layer.

Replaces the reference's torch.distributed/NCCL group machinery
(utils/distributed.py:11-41, runtime/pipe/topology.py:252-455): instead of
explicit process groups per axis, we build one ``jax.sharding.Mesh`` with named
axes ('pipe', 'data', 'model') mirroring ``PipeModelDataParallelTopology``
(topology.py:246-249), and express every collective as a sharding constraint or
``jax.lax`` collective over a named axis. XLA then lowers them onto ICI.

ZeRO sharding policy (SURVEY §7.1):
  stage 0 — params, grads, opt state replicated over 'data' (psum grads);
  stage 1 — opt state sharded over 'data';
  stage 2 — + grads reduce-scattered (psum_scatter) over 'data';
  stage 3 — + params sharded over 'data' (GSPMD gathers on use).
Sharding a pytree over 'data' picks, per leaf, the first axis divisible by the
axis size; indivisible leaves stay replicated (they are tiny: biases, norms).
"""

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"


def build_mesh(num_dp: Optional[int] = None,
               num_mp: int = 1,
               num_pp: int = 1,
               num_sp: int = 1,
               devices=None) -> Mesh:
    """Build a ('pipe','data','seq','model') mesh over the given devices.

    Axis order puts 'model' innermost so tensor-parallel collectives ride the
    fastest ICI links, then 'seq' (ring-attention k/v rotations are the next
    hottest traffic), 'pipe' outermost (stage-adjacent transfers are light),
    matching the reference's default rank-mapping intent (topology.py:246-249).
    The 'seq' axis carries sequence (context) parallelism — beyond the
    reference, which has none in v0.3.10 (SURVEY §0).
    """
    explicit = devices is not None
    devices = devices if explicit else jax.devices()
    n = len(devices)
    if num_dp is None:
        assert n % (num_mp * num_pp * num_sp) == 0, \
            "{} devices not divisible by mp={} * pp={} * sp={}".format(
                n, num_mp, num_pp, num_sp)
        num_dp = n // (num_mp * num_pp * num_sp)
    assert num_dp * num_mp * num_pp * num_sp == n, \
        "mesh {}x{}x{}x{} != {} devices".format(num_pp, num_dp, num_sp,
                                                num_mp, n)
    shape = (num_pp, num_dp, num_sp, num_mp)
    dev_array = _arrange(devices, shape, explicit)
    return Mesh(dev_array, (PIPE_AXIS, DATA_AXIS, SEQ_AXIS, MODEL_AXIS))


def _arrange(devices, shape, explicit):
    """Physical device layout for the logical mesh shape.

    On real multi-chip TPU, a flat ``jax.devices()`` reshape gives the
    innermost ('model') axis no ICI-adjacency guarantee — tensor-parallel
    collectives would hop the torus arbitrarily. Delegate to
    ``jax.experimental.mesh_utils``, which maps logical axes onto the
    physical topology (innermost axes onto nearest-neighbor rings):

    - one ICI slice (single- or multi-host — a pod slice is one ICI
      domain regardless of process count): ``create_device_mesh``;
    - multiple slices (``slice_index`` differs, i.e. DCN between them):
      the scaling-book split — the data axis carries the cross-slice
      (DCN) factor, everything else ('pipe','seq','model' and the
      per-slice remainder of 'data') stays inside each slice's ICI
      domain via ``create_hybrid_device_mesh``.

    An EXPLICIT device list keeps the caller's order (tests and
    submesh-pinning callers depend on it), and non-TPU platforms keep the
    plain reshape (virtual CPU meshes have no topology; a reorder would
    only shuffle test determinism)."""
    num_pp, num_dp, num_sp, num_mp = shape
    if explicit or not devices or devices[0].platform != "tpu" or \
            len(devices) == 1:
        return np.asarray(devices).reshape(shape)
    try:
        from jax.experimental import mesh_utils

        slices = len({getattr(d, "slice_index", 0) for d in devices})
        if slices > 1 and num_dp % slices == 0:
            return mesh_utils.create_hybrid_device_mesh(
                (num_pp, num_dp // slices, num_sp, num_mp),
                (1, slices, 1, 1), devices=devices)
        return mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception as e:  # topology solver unavailable/unhappy: still run
        from deepspeed_tpu.utils.logging import logger
        logger.warning("mesh_utils arrangement failed (%s); falling back "
                       "to flat device order", e)
        return np.asarray(devices).reshape(shape)


def default_mesh() -> Mesh:
    return build_mesh()


def replica_devices(n: int, devices=None):
    """Device per serving replica for a ServingFleet of ``n`` replicas
    (inference/fleet.py): round-robin over the visible devices, so n <=
    device_count gives each replica its own chip and n > device_count
    packs replicas fairly. On a single-device host (CPU tests) every
    replica shares the one device — the fleet then skips device_put
    entirely and replicas share the host params."""
    if n < 1:
        raise ValueError("replica count must be >= 1, got {}".format(n))
    devices = list(jax.devices()) if devices is None else list(devices)
    return [devices[i % len(devices)] for i in range(n)]


def dp_size(mesh: Mesh) -> int:
    return mesh.shape.get(DATA_AXIS, 1)


def mp_size(mesh: Mesh) -> int:
    return mesh.shape.get(MODEL_AXIS, 1)


def pp_size(mesh: Mesh) -> int:
    return mesh.shape.get(PIPE_AXIS, 1)


def sp_size(mesh: Mesh) -> int:
    return mesh.shape.get(SEQ_AXIS, 1)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch arrays: leading axis split over 'data'."""
    return NamedSharding(mesh, P(DATA_AXIS))


def _leaf_spec_over_axis(leaf, axis_name, axis_size):
    """PartitionSpec sharding the first evenly-divisible dim of ``leaf``."""
    shape = getattr(leaf, "shape", ())
    for dim, size in enumerate(shape):
        if size % axis_size == 0 and size >= axis_size:
            spec = [None] * len(shape)
            spec[dim] = axis_name
            return P(*spec)
    return P()


def tree_sharding_over_axis(mesh: Mesh, tree, axis_name=DATA_AXIS):
    """NamedSharding pytree: each leaf sharded along its first divisible dim."""
    size = mesh.shape.get(axis_name, 1)
    if size <= 1:
        rep = replicated(mesh)
        return jax.tree_util.tree_map(lambda _: rep, tree)
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(mesh, _leaf_spec_over_axis(leaf, axis_name, size)),
        tree)


# Megatron-style tensor-parallel rules: (path regex, sharded dim). Column-
# parallel layers (qkv fusion, mlp up-projection) split their OUTPUT dim and
# bias; row-parallel layers (attn/mlp down-projection) split their INPUT dim
# with a replicated bias — XLA inserts the all-reduce the reference delegates
# to the user's Megatron mpu (SURVEY §0: TP is integrated, not implemented,
# engine.py:514-525; these rules make it implemented).
DEFAULT_TP_RULES = (
    # Expert parallelism FIRST (first match wins): stacked-expert params
    # (moe/layer.py Experts) carry a leading [num_experts] axis — shard it
    # over 'model' and the MoE dispatch/combine einsums become token
    # all-to-alls under GSPMD. Ordered before the Megatron rules because
    # an expert module may itself be an attn/mlp whose inner path would
    # otherwise match them and shard the wrong dim.
    (r".*experts/.*", 0),
    (r".*(attn/c_attn|mlp/c_fc)/kernel$", 1),
    (r".*(attn/c_attn|mlp/c_fc)/bias$", 0),
    (r".*(attn|mlp)/c_proj/kernel$", 0),
)


def _tp_dim(path_str, leaf, rules, mp):
    import re
    if mp <= 1 or rules is None:
        return None
    shape = getattr(leaf, "shape", ())
    for pattern, dim in rules:
        if re.match(pattern, path_str):
            # First PATTERN match decides; an indivisible dim means this
            # leaf is replicated, not handed to a later rule — falling
            # through would shard a semantically wrong dim (e.g. a
            # stacked expert with num_experts % mp != 0 landing on the
            # Megatron mlp rule and sharding its input dim).
            if dim < len(shape) and shape[dim] % mp == 0:
                return dim
            return None
    return None


def _path_str(path):
    return "/".join(str(getattr(p, "key", getattr(p, "name", p)))
                    for p in path)


def zero_shardings(mesh: Mesh, params, stage: int, tp_rules=None):
    """(param_sharding, grad_sharding, optstate_leaf_fn) for a ZeRO stage,
    composed with tensor parallelism when the mesh has a 'model' axis.

    Returns pytrees of NamedSharding for params and grads, plus a function
    mapping an opt-state leaf-template pytree to shardings (moments follow the
    param policy for their stage). A leaf matching a TP rule carries 'model'
    on its rule dim in EVERY role; the ZeRO 'data' axis lands on the first
    other divisible dim.
    """
    mp = mp_size(mesh)
    dp = dp_size(mesh)
    if tp_rules is None and mp > 1:
        tp_rules = DEFAULT_TP_RULES

    def leaf_spec(path, leaf, with_data):
        shape = getattr(leaf, "shape", ())
        spec = [None] * len(shape)
        tp = _tp_dim(_path_str(path), leaf, tp_rules, mp)
        if tp is not None:
            spec[tp] = MODEL_AXIS
        if with_data and dp > 1:
            for dim, size in enumerate(shape):
                if dim != tp and size % dp == 0 and size >= dp:
                    spec[dim] = DATA_AXIS
                    break
        return NamedSharding(mesh, P(*spec))

    def tree_spec(tree, with_data):
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: leaf_spec(path, leaf, with_data), tree)

    param_sh = tree_spec(params, stage >= 3)
    grad_sh = tree_spec(params, stage >= 2)

    def opt_state_sharding(opt_state_template):
        return tree_spec(opt_state_template, stage >= 1)

    return param_sh, grad_sh, opt_state_sharding


def kv_cache_spec(mesh: Mesh, n_head: int, heads_dim: int = 2):
    """PartitionSpec for a slotted KV-cache plane [layers, slots, heads,
    max_len, head_dim]: heads over 'model' when divisible. Aligned with
    DEFAULT_TP_RULES' column-parallel qkv split — a tensor-sharded model's
    decode writes/reads only its local heads, and GSPMD inserts the same
    output-projection all-reduce as training. Indivisible head counts
    replicate (correct, just without the memory saving)."""
    mp = mp_size(mesh)
    if mp > 1 and n_head % mp == 0:
        spec = [None, None, None, None, None]
        spec[heads_dim] = MODEL_AXIS
        return P(*spec)
    return P()


def active_sp_axis(axis_name):
    """``axis_name`` IF the caller is being traced inside a shard_map that
    binds it; None otherwise. Lets a model switch to its sequence-parallel
    paths (ring attention, offset positions, psum'd losses) only when it
    actually runs token-sharded — init and serial eval stay untouched."""
    if axis_name is None:
        return None
    try:
        jax.lax.axis_index(axis_name)
    except NameError:
        return None
    return axis_name


def batch_partition_spec(x, dp, sp=1):
    """PartitionSpec for one batch array: leading axis over 'data' when
    divisible, second (token) axis over 'seq' when the mesh carries one.
    The single source of the batch-sharding heuristic — used by
    shard_batch's device_put AND the engine's shard_map in_specs (sparse
    grads, sequence parallelism)."""
    shape = getattr(x, "shape", ())
    if len(shape) == 0 or shape[0] % dp != 0:
        return P()
    if sp > 1 and len(shape) > 1 and shape[1] % sp == 0:
        return P(DATA_AXIS, SEQ_AXIS)
    return P(DATA_AXIS)


def shard_batch(mesh: Mesh, batch):
    """device_put a host batch: leading axis split over 'data', and the
    second (sequence) axis over 'seq' when the mesh carries one."""
    if dp_size(mesh) <= 1 and mp_size(mesh) <= 1 and pp_size(mesh) <= 1 \
            and sp_size(mesh) <= 1:
        return batch
    dp, sp = dp_size(mesh), sp_size(mesh)

    def _put(x):
        return jax.device_put(
            x, NamedSharding(mesh, batch_partition_spec(x, dp, sp)))

    return jax.tree_util.tree_map(_put, batch)
