"""Device mesh + sharding construction — the TPU-native process-group layer.

Replaces the reference's torch.distributed/NCCL group machinery
(utils/distributed.py:11-41, runtime/pipe/topology.py:252-455): instead of
explicit process groups per axis, we build one ``jax.sharding.Mesh`` with named
axes ('pipe', 'data', 'model') mirroring ``PipeModelDataParallelTopology``
(topology.py:246-249), and express every collective as a sharding constraint or
``jax.lax`` collective over a named axis. XLA then lowers them onto ICI.

ZeRO sharding policy (SURVEY §7.1):
  stage 0 — params, grads, opt state replicated over 'data' (psum grads);
  stage 1 — opt state sharded over 'data';
  stage 2 — + grads reduce-scattered (psum_scatter) over 'data';
  stage 3 — + params sharded over 'data' (GSPMD gathers on use).
Sharding a pytree over 'data' picks, per leaf, the first axis divisible by the
axis size; indivisible leaves stay replicated (they are tiny: biases, norms).
"""

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"


def build_mesh(num_dp: Optional[int] = None,
               num_mp: int = 1,
               num_pp: int = 1,
               devices=None) -> Mesh:
    """Build a ('pipe','data','model') mesh over the given devices.

    Axis order puts 'model' innermost so tensor-parallel collectives ride the
    fastest ICI links, 'pipe' outermost (stage-adjacent transfers are light),
    matching the reference's default rank-mapping intent (topology.py:246-249).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if num_dp is None:
        assert n % (num_mp * num_pp) == 0, \
            "{} devices not divisible by mp={} * pp={}".format(n, num_mp, num_pp)
        num_dp = n // (num_mp * num_pp)
    assert num_dp * num_mp * num_pp == n, \
        "mesh {}x{}x{} != {} devices".format(num_pp, num_dp, num_mp, n)
    dev_array = np.asarray(devices).reshape(num_pp, num_dp, num_mp)
    return Mesh(dev_array, (PIPE_AXIS, DATA_AXIS, MODEL_AXIS))


def default_mesh() -> Mesh:
    return build_mesh()


def dp_size(mesh: Mesh) -> int:
    return mesh.shape.get(DATA_AXIS, 1)


def mp_size(mesh: Mesh) -> int:
    return mesh.shape.get(MODEL_AXIS, 1)


def pp_size(mesh: Mesh) -> int:
    return mesh.shape.get(PIPE_AXIS, 1)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch arrays: leading axis split over 'data'."""
    return NamedSharding(mesh, P(DATA_AXIS))


def _leaf_spec_over_axis(leaf, axis_name, axis_size):
    """PartitionSpec sharding the first evenly-divisible dim of ``leaf``."""
    shape = getattr(leaf, "shape", ())
    for dim, size in enumerate(shape):
        if size % axis_size == 0 and size >= axis_size:
            spec = [None] * len(shape)
            spec[dim] = axis_name
            return P(*spec)
    return P()


def tree_sharding_over_axis(mesh: Mesh, tree, axis_name=DATA_AXIS):
    """NamedSharding pytree: each leaf sharded along its first divisible dim."""
    size = mesh.shape.get(axis_name, 1)
    if size <= 1:
        rep = replicated(mesh)
        return jax.tree_util.tree_map(lambda _: rep, tree)
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(mesh, _leaf_spec_over_axis(leaf, axis_name, size)),
        tree)


def zero_shardings(mesh: Mesh, params, stage: int):
    """(param_sharding, grad_sharding, optstate_leaf_fn) for a ZeRO stage.

    Returns pytrees of NamedSharding for params and grads, plus a function
    mapping an opt-state leaf-template pytree to shardings (moments follow the
    param policy for their stage).
    """
    rep = replicated(mesh)
    rep_tree = jax.tree_util.tree_map(lambda _: rep, params)
    sharded_tree = tree_sharding_over_axis(mesh, params, DATA_AXIS)

    param_sh = sharded_tree if stage >= 3 else rep_tree
    grad_sh = sharded_tree if stage >= 2 else rep_tree

    def opt_state_sharding(opt_state_template):
        if stage >= 1:
            return tree_sharding_over_axis(mesh, opt_state_template, DATA_AXIS)
        return jax.tree_util.tree_map(lambda _: rep, opt_state_template)

    return param_sh, grad_sh, opt_state_sharding


def shard_batch(mesh: Mesh, batch):
    """device_put a host batch with its leading axis split over 'data'."""
    if dp_size(mesh) <= 1 and mp_size(mesh) <= 1 and pp_size(mesh) <= 1:
        return batch
    sh = batch_sharding(mesh)

    def _put(x):
        if hasattr(x, "shape") and len(x.shape) > 0 and \
                x.shape[0] % dp_size(mesh) == 0:
            return jax.device_put(x, sh)
        return jax.device_put(x, replicated(mesh))

    return jax.tree_util.tree_map(_put, batch)
