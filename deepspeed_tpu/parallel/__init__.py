from deepspeed_tpu.parallel import mesh
from deepspeed_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    build_mesh,
    zero_shardings,
)
