"""Ahead-of-time elasticity: pick a total batch size valid for many chip counts.

Behavior-parity reimplementation of reference elasticity/elasticity.py:19-334.
The algorithm: candidate batch sizes are each micro-batch (and their LCM) scaled
by the largest highly-composite number that keeps the product under
``max_train_batch_size``; the winner is the candidate divisible by the most
chip counts in [min_gpus, max_gpus]. On TPU the "gpu counts" are chip counts of
the data axis; the guarantee (constant global batch across world-size changes
via gradient accumulation) carries over unchanged.
"""

import json
import math
import os
import re
from functools import reduce

from deepspeed_tpu.elasticity.config import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
)
from deepspeed_tpu.elasticity.constants import (
    DEEPSPEED_ELASTICITY_CONFIG,
    ELASTICITY,
    ENABLED,
    ENABLED_DEFAULT,
    LATEST_ELASTICITY_VERSION,
    MINIMUM_DEEPSPEED_VERSION,
)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.version import version as __version__

# Thirty-eight smallest highly composite numbers — enough to support batch
# sizes up to ~720K (reference elasticity.py:17-58).
HCN_LIST = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400, 55440,
    83160, 110880, 166320, 221760, 277200, 332640, 498960, 554400, 665280,
    720720
]


def get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    candidates = set()
    for base in base_list:
        batch_size = base
        for hcn in HCN_LIST:
            if base * hcn > max_acceptable_batch_size:
                break
            batch_size = base * hcn
        candidates.add(batch_size)
    return list(candidates)


def get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """All chip counts g in range such that batch_size is divisible by g*mb for some mb."""
    valid_gpus = set()
    for micro_batch in micro_batches:
        if batch_size % micro_batch != 0:
            continue
        max_gpus = batch_size // micro_batch
        if min_valid_gpus <= max_gpus <= max_valid_gpus:
            valid_gpus.add(max_gpus)
        for i in range(1, max_gpus // 2 + 1):
            if max_gpus % i == 0 and min_valid_gpus <= i <= max_valid_gpus:
                valid_gpus.add(i)
    return sorted(valid_gpus)


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus, max_gpus,
                        prefer_larger):
    max_valid_gpus = 0
    valid_gpus = None
    final_batch_size = int(min(micro_batches))
    for batch_size in candidate_batch_sizes:
        current_valid_gpus = get_valid_gpus(batch_size, micro_batches, min_gpus,
                                            max_gpus)
        better_count = len(current_valid_gpus) > max_valid_gpus
        tie = len(current_valid_gpus) == max_valid_gpus
        tie_break = (prefer_larger and batch_size > final_batch_size) or \
                    (not prefer_larger and batch_size < final_batch_size)
        if better_count or (tie and tie_break):
            max_valid_gpus = len(current_valid_gpus)
            valid_gpus = current_valid_gpus
            final_batch_size = batch_size
    return final_batch_size, valid_gpus


def _get_compatible_gpus_v01(micro_batches,
                             max_acceptable_batch_size,
                             min_gpus=None,
                             max_gpus=None,
                             prefer_larger=True):
    if min_gpus is None:
        min_gpus = 1
    if max_gpus is None:
        max_gpus = int(max_acceptable_batch_size / min(micro_batches))

    assert all(mb <= max_acceptable_batch_size for mb in micro_batches), (
        "All micro batches must be less than or equal to "
        "max_acceptable_batch_size: {}".format(max_acceptable_batch_size))

    lcm = reduce(lambda a, b: a * b // math.gcd(a, b), micro_batches)
    base_list = list(micro_batches) + [lcm]

    candidate_batch_sizes = get_candidate_batch_sizes(base_list,
                                                      max_acceptable_batch_size)
    return get_best_candidates(candidate_batch_sizes,
                               micro_batches,
                               min_gpus,
                               max_gpus,
                               prefer_larger)


def _parse_version(version_str):
    matched = re.search(r"^(\d+)\.(\d+)\.(\d+)", version_str)
    if matched:
        return int(matched.group(1)), int(matched.group(2)), int(matched.group(3))
    matched = re.search(r"^(\d+)\.(\d+)", version_str)
    assert matched is not None, (
        "Unable to parse version number, expecting major.minor[.patch] format "
        "but received {}".format(version_str))
    return int(matched.group(1)), int(matched.group(2)), 0


def _compatible_ds_version_check(target_deepspeed_version):
    min_version = _parse_version(MINIMUM_DEEPSPEED_VERSION)
    trg_version = _parse_version(target_deepspeed_version)
    err_str = ("Target deepspeed version of {} is not compatible with minimum "
               "version {} supporting elasticity.".format(
                   target_deepspeed_version, MINIMUM_DEEPSPEED_VERSION))
    # Component-wise gate, matching reference elasticity.py:186-198.
    if trg_version[0] < min_version[0] or trg_version[1] < min_version[1] or \
            trg_version[2] < min_version[2]:
        raise ElasticityError(err_str)
    return True


def elasticity_enabled(ds_config):
    if ELASTICITY not in ds_config:
        return False
    return ds_config[ELASTICITY].get(ENABLED, ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """Ensure the resource scheduler saw the same elastic config as the runtime."""
    if DEEPSPEED_ELASTICITY_CONFIG in os.environ:
        scheduler_elastic_config = ElasticityConfig(
            json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG]))
        runtime_elastic_config = ElasticityConfig(runtime_elastic_config_dict)
        err_str = ("Elastic config '{0}={1}' seen by resource scheduler does "
                   "not match config passed to runtime {0}={2}")
        for attr in ("max_acceptable_batch_size", "micro_batches", "version"):
            sched_val = getattr(scheduler_elastic_config, attr)
            run_val = getattr(runtime_elastic_config, attr)
            if sched_val != run_val:
                raise ElasticityConfigError(err_str.format(attr, sched_val, run_val))
    else:
        logger.warning(
            "Unable to find DEEPSPEED_ELASTICITY_CONFIG environment variable, "
            "cannot guarantee resource scheduler will scale this job using "
            "compatible chip counts.")


def compute_elastic_config(ds_config, target_deepspeed_version, world_size=0):
    """Compute (final_batch_size, valid_gpus[, micro_batch_size]) for an elastic job.

    Deterministic for a given ds_config; intended to be called by both the
    scheduler and the runtime (reference elasticity.py:240-334).
    """
    if not isinstance(ds_config, dict):
        raise ValueError(
            "Expected ds_config to be a dictionary but received a {}, "
            "containing: {}".format(type(ds_config), ds_config))

    if ELASTICITY not in ds_config:
        raise ElasticityConfigError(
            "'{}' is missing from config json, please add it if running an "
            "elastic training job.".format(ELASTICITY))

    elastic_config_dict = ds_config[ELASTICITY]
    if not elastic_config_dict.get(ENABLED, ENABLED_DEFAULT):
        raise ElasticityConfigError(
            "Elasticity is disabled, please enable it ('enabled':true) if "
            "running an elastic training job.")

    elastic_config = ElasticityConfig(elastic_config_dict)

    if float(elastic_config.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            "Attempting to run elasticity version {} but runtime only supports "
            "up to {}".format(elastic_config.version, LATEST_ELASTICITY_VERSION))

    if not _compatible_ds_version_check(target_deepspeed_version):
        raise ElasticityError(
            "Unable to run elasticity on target deepspeed version of {}, "
            "currently {}".format(target_deepspeed_version, __version__))

    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size)
        final_batch_size = int(final_batch_size)
    else:
        raise NotImplementedError(
            "Unable to find elastic logic for version: {}".format(
                elastic_config.version))

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                "World size ({}) is not valid with the current list of valid "
                "chip counts: {}".format(world_size, valid_gpus))
        # Pick the largest micro batch size that evenly divides the per-chip batch.
        micro_batch_size = None
        for mbsz in sorted(set(elastic_config.micro_batches), reverse=True):
            if final_batch_size // world_size % mbsz == 0:
                micro_batch_size = mbsz
                break
        assert micro_batch_size is not None, (
            "Unable to find divisible micro batch size world_size={}, "
            "final_batch_size={}, and micro_batches={}.".format(
                world_size, final_batch_size, elastic_config.micro_batches))
        return final_batch_size, valid_gpus, micro_batch_size

    return final_batch_size, valid_gpus
