"""Elasticity config object + exception hierarchy (reference elasticity/config.py)."""

import json

from deepspeed_tpu.elasticity.constants import (
    ENABLED,
    ENABLED_DEFAULT,
    IGNORE_NON_ELASTIC_BATCH_INFO,
    IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT,
    MAX_ACCEPTABLE_BATCH_SIZE,
    MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT,
    MAX_GPUS,
    MAX_GPUS_DEFAULT,
    MICRO_BATCHES,
    MICRO_BATCHES_DEFAULT,
    MIN_GPUS,
    MIN_GPUS_DEFAULT,
    MIN_TIME,
    MIN_TIME_DEFAULT,
    PREFER_LARGER_BATCH,
    PREFER_LARGER_BATCH_DEFAULT,
    VERSION,
    VERSION_DEFAULT,
)


class ElasticityError(Exception):
    """Base exception for all elasticity related errors."""


class ElasticityConfigError(ElasticityError):
    """Elasticity configuration error."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size incompatible with the given elastic config."""


class ElasticityConfig:
    """Elastic config parsed from the ``elasticity`` block of ds_config.

    When enabled, ``max_train_batch_size`` and ``micro_batch_sizes`` are
    required; validation matches reference elasticity/config.py:48-105.
    """

    def __init__(self, param_dict):
        self.enabled = param_dict.get(ENABLED, ENABLED_DEFAULT)
        if self.enabled:
            if MAX_ACCEPTABLE_BATCH_SIZE not in param_dict:
                raise ElasticityConfigError(
                    "Elasticity config missing {}".format(MAX_ACCEPTABLE_BATCH_SIZE))
            if MICRO_BATCHES not in param_dict:
                raise ElasticityConfigError(
                    "Elasticity config missing {}".format(MICRO_BATCHES))
            self.max_acceptable_batch_size = param_dict[MAX_ACCEPTABLE_BATCH_SIZE]
            self.micro_batches = param_dict[MICRO_BATCHES]
        else:
            self.max_acceptable_batch_size = param_dict.get(
                MAX_ACCEPTABLE_BATCH_SIZE, MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
            self.micro_batches = param_dict.get(MICRO_BATCHES, MICRO_BATCHES_DEFAULT)

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                "Elasticity expected value of {} to be a list of micro batches, "
                "instead is: {}, containing: {}".format(
                    MICRO_BATCHES, type(self.micro_batches), self.micro_batches))
        if not all(isinstance(m, int) for m in self.micro_batches):
            raise ElasticityConfigError(
                "Elasticity expected {} to only contain a list of integers, "
                "instead contains: {}".format(MICRO_BATCHES, self.micro_batches))
        if not all(m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                "Elasticity expected {} to only contain positive integers, "
                "instead contains: {}".format(MICRO_BATCHES, self.micro_batches))

        self.min_gpus = param_dict.get(MIN_GPUS, MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(MAX_GPUS, MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError(
                "Elasticity min/max gpus must be > 0, given min_gpus: {}, "
                "max_gpus: {}".format(self.min_gpus, self.max_gpus))
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                "Elasticity min_gpus cannot be greater than max_gpus, given "
                "min_gpus: {}, max_gpus: {}".format(self.min_gpus, self.max_gpus))

        self.min_time = param_dict.get(MIN_TIME, MIN_TIME_DEFAULT)
        if self.min_time < 0:
            raise ElasticityConfigError(
                "Elasticity min time needs to be >= 0: given {}".format(self.min_time))

        self.version = param_dict.get(VERSION, VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(PREFER_LARGER_BATCH,
                                                       PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            IGNORE_NON_ELASTIC_BATCH_INFO, IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
