"""Pipeline-parallelism re-exports (reference deepspeed/pipe/__init__.py)."""
try:
    from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule, TiedLayerSpec
except ImportError:  # pipeline engine lands in a later milestone
    class PipelineModule:  # placeholder so isinstance checks work
        _placeholder = True

    LayerSpec = None
    TiedLayerSpec = None
