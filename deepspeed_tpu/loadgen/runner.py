"""Open-loop sustained-load driver.

OPEN loop means arrivals follow the workload's schedule, not the
engine's pace: ``run()`` calls ``engine.submit()`` the moment each
request's ``arrival_s`` passes, whatever the backlog looks like, and
harvests completions separately. A closed-loop driver (next request
only after the previous answer) self-throttles into exactly the load
the engine can absorb — it can NEVER observe queueing collapse, which
is the one thing a sustained-load harness exists to observe. Under open
loop, saturation shows up honestly: queue depth climbs window over
window, TTFT p99 grows without bound, and past ``max_queue`` the engine
sheds (scheduler.QueueFull) — the runner records each shed as a sample
row rather than dying, because shed traffic IS the signal.

One ``TimeseriesCollector.tick()`` per loop iteration turns the run
into per-window curves; one sample record per request (submitted or
shed) carries the per-request view. ``loadgen/report.py`` folds both
into the SLO report.

CHAOS MODE: pass ``chaos_plan`` (an inference.faults.FaultPlan) and the
runner arms it on the engine once ``chaos_after_s`` of run time has
passed — faults fire MID-RUN, against a live mixed batch, which is the
only honest way to measure recovery (a fault against an idle engine
recovers for free). The engine needs ``fault_injection=True``; chaos
runs want the REAL clock (the engine's recovery timestamps are
``time.time()`` and the runner converts them to run-relative). The
result then carries the recovery intervals and ``requests_lost`` — the
number the recovery invariant pins at 0 — and report.py folds both
into a ``chaos`` section with SLO attainment split during/outside
recovery.
"""

import dataclasses
import time

from deepspeed_tpu.inference.scheduler import QueueFull
from deepspeed_tpu.telemetry import TimeseriesCollector


@dataclasses.dataclass
class RunResult:
    """Everything one sustained run produced: per-request ``samples``
    (dict rows, shed included), the collector's per-window records, and
    the run-level tallies report.py aggregates."""

    samples: list
    windows: list
    collector: object
    wall_s: float
    submitted: int
    completed: int
    shed: int
    tokens_out: int
    # Chaos/recovery facts (empty/zero on fault-free runs): recovery
    # intervals in RUN-RELATIVE seconds (t_start_s/t_end_s/duration_s +
    # error/replayed), and accepted requests that reached NO terminal
    # phase by run end — the recovery invariant demands 0.
    recovery: list = dataclasses.field(default_factory=list)
    requests_lost: int = 0
    faults_injected: int = 0
    # Prefix-cache facts (zero on engines/fleets without one): counter
    # DELTAS across this run — probe hits/misses, bytes the fleet
    # shipped in cross-replica adoptions, and requests whose route was
    # won (or made good) by prefix affinity.
    prefix_hits: int = 0
    prefix_misses: int = 0
    prefix_bytes_shipped: int = 0
    affinity_routed: int = 0
    # Disaggregated-serving facts (zero on single engines and all-mixed
    # fleets): counter deltas across this run — prompts captured off
    # prefill replicas, re-prefill fallbacks (no decode-capable
    # survivor), and the KV bytes the handoff records shipped.
    handoffs: int = 0
    handoff_fallbacks: int = 0
    handoff_bytes_shipped: int = 0
    # Front-door facts (zero without one): counter deltas across this
    # run — batch sessions parked in the swapped phase to protect a
    # latency budget, and their later bit-identical resumes.
    preemptions: int = 0
    preempt_resumes: int = 0
    # SLO alerting facts (empty without an ``alerts`` manager): every
    # rising-edge record the manager saw during this run, in firing
    # order — telemetry/alerts.py's ``fired()`` schema.
    alerts_fired: list = dataclasses.field(default_factory=list)
    # Adapter facts (schema v6, zero/empty on plain GPT-2): which
    # ModelAdapter served the run, per-expert dispatch totals summed
    # across replicas (MoE), the long-context sparse threshold in force
    # (0 = dense), and KV host-offload swap counter deltas — the
    # offloaded-page evidence for long-context capacity runs.
    adapter: str = None
    expert_load: list = dataclasses.field(default_factory=list)
    sparse_decode_threshold: int = 0
    swap_outs: int = 0
    swap_ins: int = 0
    # Paged-KV facts (schema v7; false/zero on dense engines): the page
    # quantum and arena size from ``engine.kv_page_stats()``, the
    # high-water page count across the run, and live-tokens-over-mapped-
    # capacity at that peak (the fragmentation bound).
    paged: bool = False
    kv_page_len: int = 0
    kv_pages_total: int = 0
    kv_pages_peak: int = 0
    kv_page_utilization: float = None


def _sample_row(lr, req, shed_reason=None):
    """One per-request record from the scheduler Request's timestamp
    trail (submit/first-token/finish are stamped by the engine at
    harvest time — the runner only reads them back). ``priority``/
    ``tenant`` come from the workload tags (the request echoes them on
    tagged surfaces; the tags are authoritative for shed rows, which
    never got a request); ``shed_reason`` is the structured QueueFull
    reason for shed rows (None on legacy untagged sheds)."""
    row = {
        "arrival_s": lr.arrival_s,
        "prompt_tokens": int(lr.prompt.size),
        "max_new_tokens": int(lr.max_new_tokens),
        "shed": req is None,
        "shed_reason": shed_reason,
        "rid": None if req is None else req.rid,
        "priority": getattr(lr, "priority", None),
        "tenant": getattr(lr, "tenant", None),
        "ttft_s": None,
        "e2e_s": None,
        "itl_s": None,
        "tokens_out": 0,
        "completed": False,
        "phase": None,
    }
    if req is None:
        return row
    row["phase"] = req.phase
    row["tokens_out"] = len(req.tokens)
    if req.first_token_time is not None:
        row["ttft_s"] = req.first_token_time - req.submit_time
    if req.finish_time is not None:
        # ``completed`` means DONE — a deadline-expired or cancelled
        # request has a finish_time too but never delivered its answer,
        # and must not count toward completion or SLO attainment.
        row["completed"] = req.phase == "done"
        row["e2e_s"] = req.finish_time - req.submit_time
        if req.first_token_time is not None and len(req.tokens) > 1:
            row["itl_s"] = ((req.finish_time - req.first_token_time) /
                            (len(req.tokens) - 1))
    return row


class SustainedRunner(object):
    """Drive ``engine`` with ``spec``'s request stream, open loop.

    The caller owns warmup: compile + ``recompile_detector.mark_warm()``
    + ``engine.metrics(reset=True)`` BEFORE ``run()``, so neither
    compile time nor warmup traffic pollutes the first window (the
    collector owns the registry's window state from ``start()`` on —
    see telemetry/timeseries.py).

    ``clock``/``sleep`` are injectable for tests; ``max_steps`` is a
    hard iteration backstop so a wedged engine fails the harness loudly
    instead of hanging CI.
    """

    def __init__(self, engine, spec, window_seconds=1.0, max_windows=512,
                 collector=None, max_steps=None, clock=time.time,
                 sleep=time.sleep, chaos_plan=None, chaos_after_s=0.0,
                 chaos_replica=None, alerts=None):
        self.engine = engine
        self.spec = spec
        self._clock = clock
        self._sleep = sleep
        self.max_steps = max_steps
        # Optional telemetry.alerts.AlertManager: evaluated once per
        # loop iteration (right after the collector tick, so a freshly
        # closed window is scored immediately) and its rising edges
        # land in RunResult.alerts_fired. A fleet target usually wires
        # its own manager into _tick() instead — pass it here too and
        # evaluate() stays idempotent (windows score once).
        self.alerts = alerts
        # Chaos mode (module docstring): arm ``chaos_plan`` on the
        # engine once ``chaos_after_s`` run seconds pass. Fault steps
        # count from ARMING, so the plan is written relative to the
        # chaos point, not the run start. ``chaos_replica`` targets one
        # replica of a ServingFleet (kill-a-replica-mid-run chaos);
        # None keeps the single-engine call shape.
        self.chaos_plan = chaos_plan
        self.chaos_after_s = chaos_after_s
        self.chaos_replica = chaos_replica
        self.collector = collector or TimeseriesCollector(
            engine.telemetry, window_seconds=window_seconds,
            capacity=max_windows, clock=clock)

    def run(self):
        pending = self.spec.requests() if hasattr(self.spec, "requests") \
            else list(self.spec)
        handles = []   # (LoadRequest, Request-or-None, shed_reason) rows
        t0 = self._clock()
        self.collector.start(t0)
        i, steps, shed = 0, 0, 0
        injector = None
        recoveries_at_start = len(getattr(self.engine, "recovery_log", []))
        counters = getattr(self.engine, "counters", None)

        def _counter(name):
            if counters is not None and name in counters:
                return counters[name]
            return 0

        faults_at_start = _counter("faults_injected")
        # Paged-KV poll state: kv_page_stats is the single-engine
        # surface (a fleet aggregates per-replica; its report rows stay
        # at the dense defaults), _live_tokens the utilization numerator.
        page_stats_fn = getattr(self.engine, "kv_page_stats", None)
        live_tokens_fn = getattr(self.engine, "_live_tokens", None)
        pages_peak, page_util = 0, None
        prefix_at_start = {n: _counter(n) for n in (
            "prefix_hits", "prefix_misses", "prefix_bytes_shipped",
            "affinity_routed", "handoffs", "handoff_fallbacks",
            "handoff_bytes_shipped", "preemptions", "preempt_resumes",
            "swap_outs", "swap_ins")}
        while i < len(pending) or not self.engine.idle:
            now = self._clock() - t0
            if (self.chaos_plan is not None and injector is None
                    and now >= self.chaos_after_s):
                if self.chaos_replica is not None:
                    injector = self.engine.inject_faults(
                        self.chaos_plan, replica=self.chaos_replica)
                else:
                    injector = self.engine.inject_faults(self.chaos_plan)
            # Submit everything whose arrival time has passed — open
            # loop: the schedule, not the backlog, decides.
            while i < len(pending) and pending[i].arrival_s <= now:
                lr = pending[i]
                kw = {}
                # Tagged workloads ride the front-door surface; the
                # legacy untagged call shape stays byte-identical.
                if getattr(lr, "priority", None) is not None:
                    kw["priority"] = lr.priority
                if getattr(lr, "tenant", None) is not None:
                    kw["tenant"] = lr.tenant
                try:
                    handles.append((lr, self.engine.submit(
                        lr.prompt, max_new_tokens=lr.max_new_tokens,
                        temperature=lr.temperature, seed=lr.seed,
                        **kw), None))
                except QueueFull as exc:
                    shed += 1
                    handles.append((lr, None,
                                    getattr(exc, "reason", None)))
                i += 1
            if self.engine.idle:
                # Nothing in flight: sleep to the next arrival, but
                # never past the current window's close (the curve must
                # keep its cadence through quiet gaps).
                gap = pending[i].arrival_s - (self._clock() - t0)
                if gap > 0:
                    self._sleep(min(gap, self.collector.window_seconds))
            else:
                self.engine.step()
                steps += 1
                if page_stats_fn is not None:
                    pst = page_stats_fn()
                    if pst is not None and pst["pages_in_use"] > pages_peak:
                        pages_peak = pst["pages_in_use"]
                        if live_tokens_fn is not None:
                            page_util = (live_tokens_fn() /
                                         float(pst["pages_in_use"] *
                                               pst["page_len"]))
                if self.max_steps is not None and steps > self.max_steps:
                    raise RuntimeError(
                        "sustained run exceeded max_steps={} with {} "
                        "requests outstanding — engine wedged?".format(
                            self.max_steps, len(pending) - i +
                            sum(1 for _, r, _ in handles
                                if r is not None and not r.done)))
            self.collector.tick()
            if self.alerts is not None:
                self.alerts.evaluate()
        self.collector.sample()   # flush the tail window
        if self.alerts is not None:
            self.alerts.evaluate()
        wall = self._clock() - t0
        samples = [_sample_row(lr, req, reason)
                   for lr, req, reason in handles]
        # Recovery intervals from this run only, converted to run-
        # relative seconds (the engine stamps time.time(); chaos runs
        # use the real clock — module docstring).
        recovery = [
            {"t_start_s": round(r["t_start"] - t0, 6),
             "t_end_s": round(r["t_end"] - t0, 6),
             "duration_s": r["duration_s"],
             "error": r["error"], "replayed": r["replayed"]}
            for r in getattr(self.engine, "recovery_log",
                             [])[recoveries_at_start:]]
        # The recovery invariant's bottom line: every ACCEPTED request
        # must reach a terminal phase — done, or deliberately shed
        # (expired / cancelled). Anything else was lost by the engine.
        lost = sum(1 for _, r, _ in handles
                   if r is not None and r.phase not in
                   ("done", "expired", "cancelled"))
        # Adapter facts: name + sparse threshold off the (shared)
        # adapter instance; per-expert dispatch gauges summed across
        # replicas out of the registry snapshot (keys look like
        # ``moe_expert_load{expert=2,replica=0}`` on a fleet).
        final_page_stats = (None if page_stats_fn is None
                            else page_stats_fn())
        adapter_obj = getattr(self.engine, "adapter", None)
        expert_load = {}
        reg = getattr(self.engine, "telemetry", None)
        if adapter_obj is not None and reg is not None:
            for key, val in reg.snapshot().items():
                if not key.startswith("moe_expert_load{"):
                    continue
                for part in key[key.index("{") + 1:-1].split(","):
                    k, _, v = part.partition("=")
                    if k == "expert":
                        e = int(v)
                        expert_load[e] = expert_load.get(e, 0.0) + val
        return RunResult(
            samples=samples,
            windows=self.collector.windows(),
            collector=self.collector,
            wall_s=wall,
            submitted=sum(1 for _, r, _ in handles if r is not None),
            completed=sum(1 for s in samples if s["completed"]),
            shed=shed,
            tokens_out=sum(s["tokens_out"] for s in samples),
            recovery=recovery,
            requests_lost=lost,
            faults_injected=(0 if counters is None or
                             "faults_injected" not in counters else
                             counters["faults_injected"] - faults_at_start),
            prefix_hits=_counter("prefix_hits")
            - prefix_at_start["prefix_hits"],
            prefix_misses=_counter("prefix_misses")
            - prefix_at_start["prefix_misses"],
            prefix_bytes_shipped=_counter("prefix_bytes_shipped")
            - prefix_at_start["prefix_bytes_shipped"],
            affinity_routed=_counter("affinity_routed")
            - prefix_at_start["affinity_routed"],
            handoffs=_counter("handoffs")
            - prefix_at_start["handoffs"],
            handoff_fallbacks=_counter("handoff_fallbacks")
            - prefix_at_start["handoff_fallbacks"],
            handoff_bytes_shipped=_counter("handoff_bytes_shipped")
            - prefix_at_start["handoff_bytes_shipped"],
            preemptions=_counter("preemptions")
            - prefix_at_start["preemptions"],
            preempt_resumes=_counter("preempt_resumes")
            - prefix_at_start["preempt_resumes"],
            alerts_fired=([] if self.alerts is None
                          else self.alerts.fired()),
            adapter=(None if adapter_obj is None
                     else getattr(adapter_obj, "name", None)),
            expert_load=[expert_load[e] for e in sorted(expert_load)],
            sparse_decode_threshold=int(
                getattr(adapter_obj, "threshold", 0) or 0),
            swap_outs=_counter("swap_outs")
            - prefix_at_start["swap_outs"],
            swap_ins=_counter("swap_ins")
            - prefix_at_start["swap_ins"],
            paged=final_page_stats is not None,
            kv_page_len=(0 if final_page_stats is None
                         else int(final_page_stats["page_len"])),
            kv_pages_total=(0 if final_page_stats is None
                            else int(final_page_stats["pages_total"])),
            kv_pages_peak=pages_peak,
            kv_page_utilization=page_util)
