"""SLO evaluation: budgets, attainment, goodput.

Throughput alone rewards the wrong thing — an engine that batches so
aggressively every request waits seconds for its first token posts
GREAT tokens/sec. The serving-quality number that resists that gaming
is GOODPUT: tokens per second per chip counted ONLY from requests that
met their latency budgets. A shed request (QueueFull) met nothing — it
counts against attainment and contributes zero goodput, which is what
makes overload visible in the headline number instead of hidden in a
side tally.

Budgets are per-REQUEST checks (this request's TTFT and mean ITL inside
budget?), aggregated into attainment; the p99 curves in the windowed
report tell you WHEN the misses happened.
"""

import dataclasses


@dataclasses.dataclass(frozen=True)
class SLO:
    """Latency budgets, milliseconds. ``None`` disables that check."""

    ttft_p99_ms: float = 1500.0
    itl_p99_ms: float = 150.0

    def meets(self, sample):
        """Does one runner sample row meet every enabled budget? Shed
        and unfinished requests never do; a one-token request has no ITL
        and is judged on TTFT alone."""
        if sample["shed"] or not sample["completed"]:
            return False
        if self.ttft_p99_ms is not None:
            if sample["ttft_s"] is None:
                return False
            if sample["ttft_s"] * 1e3 > self.ttft_p99_ms:
                return False
        if self.itl_p99_ms is not None and sample["itl_s"] is not None:
            if sample["itl_s"] * 1e3 > self.itl_p99_ms:
                return False
        return True

    def to_json(self):
        return dataclasses.asdict(self)


def evaluate(samples, slo, wall_s, chips=1):
    """Fold runner samples + budgets into the SLO section of a report.

    ``goodput_tokens_per_sec``: tokens from SLO-meeting requests over
    the run's wall clock; ``_per_chip`` divides by ``chips`` so numbers
    compare across pod sizes."""
    total = len(samples)
    shed = sum(1 for s in samples if s["shed"])
    met = [s for s in samples if slo.meets(s)]
    good_tokens = sum(s["tokens_out"] for s in met)
    wall = max(float(wall_s), 1e-9)
    return {
        "budgets": slo.to_json(),
        "requests": total,
        "shed": shed,
        "slo_met": len(met),
        "attainment": (len(met) / total) if total else None,
        "goodput_tokens_per_sec": good_tokens / wall,
        "goodput_tokens_per_sec_per_chip":
            good_tokens / wall / max(int(chips), 1),
    }
