"""deepspeed_tpu.loadgen — sustained-load harness over the serving engine.

The telemetry package (PR 5) made the engine observable; this package
asks it the questions that matter under LOAD:

- ``WorkloadSpec`` (workload.py): seeded, fully deterministic request
  streams — Poisson/burst/ramp arrivals, heavy-tail lognormal/Zipf
  prompt+output length mixes, JSONL trace replay.
- ``SustainedRunner`` (runner.py): open-loop driver — submits on the
  workload's schedule regardless of backlog, records QueueFull sheds as
  signal, ticks a ``TimeseriesCollector`` into per-window curves. Chaos
  mode (``chaos_plan``/``chaos_after_s``) arms a fault plan mid-run and
  the report grows a ``chaos`` section — recovery time, requests lost,
  SLO attainment during vs outside recovery (docs/RESILIENCE.md).
- ``SLO`` / ``evaluate`` (slo.py): TTFT/ITL budgets, attainment, and
  goodput (tokens from SLO-meeting requests per second per chip).
- ``build_report`` / ``saturation_sweep`` / ``regression_gate``
  (report.py): the JSON report artifact, the stepped-rate capacity
  sweep, and the noise-aware A/B gate whose thresholds come from each
  run's own per-window variance.

``bench.py --sustained`` wires the whole stack end to end (a ``--smoke``
variant runs on CPU in CI) and ``bench.py --chaos-smoke`` does the same
with one injected fatal step fault, asserting the recovery invariant;
docs/BENCHMARKING.md is the methodology page.
"""

from deepspeed_tpu.loadgen.report import (
    GATE_DEFAULT_METRICS,
    SCHEMA_VERSION,
    build_report,
    regression_gate,
    saturation_sweep,
)
from deepspeed_tpu.loadgen.runner import RunResult, SustainedRunner
from deepspeed_tpu.loadgen.slo import SLO, evaluate
from deepspeed_tpu.loadgen.workload import (
    LoadRequest,
    MixedWorkload,
    WorkloadSpec,
    replay_trace,
    save_trace,
)
from deepspeed_tpu.telemetry import TimeseriesCollector

__all__ = [
    "LoadRequest",
    "MixedWorkload",
    "WorkloadSpec",
    "replay_trace",
    "save_trace",
    "SustainedRunner",
    "RunResult",
    "SLO",
    "evaluate",
    "TimeseriesCollector",
    "SCHEMA_VERSION",
    "GATE_DEFAULT_METRICS",
    "build_report",
    "saturation_sweep",
    "regression_gate",
]
