"""Seeded workload specs — deterministic open-loop request streams.

A ``WorkloadSpec`` describes the TRAFFIC, not the engine: when requests
arrive (Poisson / burst / ramp arrival processes, or a JSONL trace
replayed verbatim), how long their prompts are and how many tokens they
want back (heavy-tail lognormal / Zipf mixes — production length
distributions are long-tailed, and a harness that offers uniform
lengths never sees the head-of-line effects the tail causes), and what
the prompt tokens actually are (repetition-heavy phrase tiling by
default, so n-gram speculative drafting has self-matches to find — the
same choice ``bench.py --serve`` makes).

Everything is FULLY DETERMINISTIC per ``seed``: two calls to
``spec.requests()`` — on different days, different machines — produce
identical arrival times, identical token ids, identical budgets. That
determinism is what makes an A/B comparable at all (two runs that
served different streams measure the streams, not the code) and is
pinned by tests/unit/test_loadgen.py.

The spec is engine-agnostic and jax-free: ``requests()`` returns plain
``LoadRequest`` rows the open-loop runner (runner.py) feeds to
``engine.submit()`` at their scheduled times.
"""

import dataclasses
import json
import math
from typing import Optional

import numpy as np

ARRIVALS = ("poisson", "burst", "ramp", "trace")
LENGTH_DISTS = ("fixed", "lognormal", "zipf")


@dataclasses.dataclass(eq=False)
class LoadRequest:
    """One scheduled request: WHEN it arrives and WHAT it asks for.

    ``priority``/``tenant`` are front-door tags (inference/frontdoor):
    None keeps the legacy untagged stream and the runner's legacy
    submit() call shape byte-for-byte."""

    arrival_s: float
    prompt: np.ndarray          # int32 token ids
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    priority: Optional[str] = None
    tenant: Optional[str] = None


def _lengths(rng, dist, n, mean, sigma, zipf_a, lo, hi):
    """``n`` integer lengths in [lo, hi] from the named distribution.

    - ``lognormal``: mu chosen so the UNDERLYING mean is ``mean``
      (heavier sigma = heavier right tail, same center).
    - ``zipf``: ``lo * Zipf(a)`` — most draws sit at ``lo``, a power-law
      tail reaches toward ``hi`` (the shared-prefix-plus-occasional-
      novel-monster shape of real prompt traffic).
    - ``fixed``: every length is ``mean``.
    """
    if lo < 1 or hi < lo:
        raise ValueError("length bounds must satisfy 1 <= lo <= hi, got "
                         "[{}, {}]".format(lo, hi))
    if dist == "fixed":
        lens = np.full(n, float(mean))
    elif dist == "lognormal":
        mu = math.log(max(float(mean), 1.0)) - sigma * sigma / 2.0
        lens = rng.lognormal(mu, sigma, size=n)
    elif dist == "zipf":
        lens = float(lo) * rng.zipf(zipf_a, size=n)
    else:
        raise ValueError("unknown length distribution {!r}; one of "
                         "{}".format(dist, LENGTH_DISTS))
    return np.clip(np.rint(lens), lo, hi).astype(int)


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    # Arrival process: 'poisson' (exponential gaps at ``rate``), 'burst'
    # (groups of ``burst_size`` simultaneous arrivals every
    # ``burst_gap_s``), 'ramp' (Poisson whose intensity ramps linearly
    # ``ramp_from`` -> ``rate`` across the stream — the saturation-sweep
    # shape in one run), 'trace' (replay ``trace_path`` JSONL verbatim).
    arrival: str = "poisson"
    # Mean arrivals/second (poisson), final rate (ramp); unused by
    # 'burst' (its rate is burst_size / burst_gap_s) and 'trace'.
    rate: float = 8.0
    n_requests: int = 64
    burst_size: int = 8
    burst_gap_s: float = 1.0
    ramp_from: float = 1.0
    # Prompt-length mix (tokens).
    prompt_dist: str = "lognormal"
    prompt_mean: int = 64
    prompt_sigma: float = 0.6
    prompt_zipf_a: float = 2.2
    prompt_min: int = 1
    prompt_max: int = 256
    # Output-budget mix (max_new_tokens per request).
    output_dist: str = "lognormal"
    output_mean: int = 64
    output_sigma: float = 0.6
    output_zipf_a: float = 2.2
    output_min: int = 1
    output_max: int = 128
    vocab_size: int = 50257
    # Prompt content: > 0 tiles a per-request random phrase of this many
    # tokens to the prompt length (repetition-heavy — text repeats, and
    # the n-gram drafter needs matches); 0 draws uniform random tokens.
    phrase_len: int = 8
    # Shared system-prompt pool: > 0 pre-draws this many fixed prefixes
    # of ``prefix_tokens`` tokens each and prepends one to every prompt,
    # chosen by a Zipf(``prefix_zipf_a``) rank — a few prefixes dominate
    # (the shape of real system-prompt traffic), which is exactly what a
    # shared-prefix KV cache exploits. 0 disables (and keeps streams
    # byte-identical to specs that predate this knob: the pool draws
    # come AFTER every legacy draw in RandomState order).
    prefix_pool: int = 0
    prefix_tokens: int = 32
    prefix_zipf_a: float = 1.5
    temperature: float = 0.0
    # JSONL trace to replay when arrival == 'trace' (see replay_trace).
    trace_path: Optional[str] = None
    seed: int = 0

    def __post_init__(self):
        if self.arrival not in ARRIVALS:
            raise ValueError("unknown arrival process {!r}; one of "
                             "{}".format(self.arrival, ARRIVALS))
        if self.arrival == "trace":
            if not self.trace_path:
                raise ValueError(
                    "arrival='trace' requires trace_path (a JSONL file — "
                    "see loadgen.workload.save_trace)")
        else:
            if self.n_requests < 1:
                raise ValueError("n_requests must be >= 1, got "
                                 "{}".format(self.n_requests))
            if self.rate <= 0:
                raise ValueError("rate must be > 0, got "
                                 "{}".format(self.rate))
        if self.arrival == "burst" and (self.burst_size < 1 or
                                        self.burst_gap_s <= 0):
            raise ValueError("burst needs burst_size >= 1 and "
                             "burst_gap_s > 0")
        if self.arrival == "ramp" and self.ramp_from <= 0:
            raise ValueError("ramp_from must be > 0, got "
                             "{}".format(self.ramp_from))
        for d in (self.prompt_dist, self.output_dist):
            if d not in LENGTH_DISTS:
                raise ValueError("unknown length distribution {!r}; one "
                                 "of {}".format(d, LENGTH_DISTS))
        if self.prefix_pool < 0:
            raise ValueError("prefix_pool must be >= 0, got "
                             "{}".format(self.prefix_pool))
        if self.prefix_pool > 0:
            if self.prefix_tokens < 1:
                raise ValueError("prefix_tokens must be >= 1 when "
                                 "prefix_pool > 0, got "
                                 "{}".format(self.prefix_tokens))
            if self.prefix_zipf_a <= 1.0:
                raise ValueError("prefix_zipf_a must be > 1, got "
                                 "{}".format(self.prefix_zipf_a))

    # ---------------------------------------------------------- arrivals

    def _arrival_times(self, rng):
        n = self.n_requests
        if self.arrival == "poisson":
            return np.cumsum(rng.exponential(1.0 / self.rate, size=n))
        if self.arrival == "burst":
            group = np.arange(n) // self.burst_size
            return group.astype(float) * self.burst_gap_s
        # ramp: a Poisson process whose intensity ramps linearly from
        # ramp_from to rate across the stream — gap i is an exponential
        # draw at the instantaneous rate.
        rates = np.linspace(self.ramp_from, self.rate, n)
        return np.cumsum(rng.exponential(1.0, size=n) / rates)

    # ---------------------------------------------------------- requests

    def requests(self):
        """The full request stream, arrival-sorted. Deterministic per
        ``seed`` — every random draw comes from one RandomState(seed)
        consumed in a fixed order."""
        if self.arrival == "trace":
            return replay_trace(self.trace_path,
                                vocab_size=self.vocab_size, seed=self.seed)
        rng = np.random.RandomState(self.seed)
        arrivals = self._arrival_times(rng)
        plens = _lengths(rng, self.prompt_dist, self.n_requests,
                         self.prompt_mean, self.prompt_sigma,
                         self.prompt_zipf_a, self.prompt_min,
                         self.prompt_max)
        outs = _lengths(rng, self.output_dist, self.n_requests,
                        self.output_mean, self.output_sigma,
                        self.output_zipf_a, self.output_min,
                        self.output_max)
        # Shared prefixes are drawn ONCE, after all legacy draws, so a
        # prefix_pool=0 spec consumes the RandomState identically to
        # specs written before the knob existed.
        pool = None
        if self.prefix_pool > 0:
            pool = rng.randint(0, self.vocab_size,
                               size=(self.prefix_pool, self.prefix_tokens))
        reqs = []
        for i in range(self.n_requests):
            n = int(plens[i])
            prefix = None
            if pool is not None:
                # Zipf rank folded onto the pool: rank 1 (most of the
                # mass) is prefix 0, so a small number of prefixes serve
                # most requests.
                rank = int(rng.zipf(self.prefix_zipf_a))
                prefix = pool[(rank - 1) % self.prefix_pool]
                n = max(n - prefix.size, 0)
            if n == 0:
                toks = np.empty((0,), dtype=int)
            elif self.phrase_len > 0:
                phrase = rng.randint(0, self.vocab_size,
                                     size=(min(self.phrase_len, n),))
                toks = np.tile(phrase, -(-n // phrase.size))[:n]
            else:
                toks = rng.randint(0, self.vocab_size, size=(n,))
            if prefix is not None:
                toks = np.concatenate([prefix, toks])
            reqs.append(LoadRequest(
                arrival_s=float(arrivals[i]),
                prompt=toks.astype(np.int32),
                max_new_tokens=int(outs[i]),
                temperature=self.temperature,
                seed=int(rng.randint(0, 2 ** 31 - 1))))
        return reqs

    def to_json(self):
        """JSON-safe echo of the spec for run reports (a report must
        carry the workload that produced it — a gate comparing runs of
        DIFFERENT workloads measures the workloads)."""
        return dataclasses.asdict(self)

    @classmethod
    def template_heavy(cls, **overrides):
        """Template-dominated traffic: a SMALL pool of long shared
        system prompts (Zipf-skewed, so two templates carry most of the
        mass) with short unique tails — the workload shape the fleet
        prefix directory and prefix-affinity routing are built for. A
        prompt is ``prefix_tokens`` shared tokens plus a 2..~48-token
        per-request tail (the lognormal prompt-length draw minus the
        prefix; tails are unique because each request tiles its own
        phrase draw). Deterministic per ``seed`` like every spec —
        same-seeded calls produce byte-identical streams. Tests override
        geometry down (prefix_tokens, prompt bounds) to fit tiny-engine
        max_len; the defaults fit the serve-bench engine."""
        params = dict(
            arrival="poisson",
            rate=8.0,
            n_requests=64,
            prefix_pool=4,
            prefix_tokens=48,
            prefix_zipf_a=1.3,
            prompt_dist="lognormal",
            prompt_mean=60,
            prompt_sigma=0.15,
            prompt_min=50,
            prompt_max=96,
            phrase_len=4,
            output_dist="lognormal",
            output_mean=16,
            output_sigma=0.3,
            output_min=4,
            output_max=32,
        )
        params.update(overrides)
        return cls(**params)

    @classmethod
    def long_context(cls, **overrides):
        """Long-context traffic: heavy-tailed lognormal prompt lengths
        whose right tail crosses 32k tokens — the workload the
        LongContextAdapter's block-sparse decode and KV host-offload
        exist for. Most requests sit in the few-thousand-token body
        (sigma 1.4 on an 8k mean puts ~4-5% of draws past 32k), so a
        run exercises BOTH regimes: dense below the sparse threshold
        and block-sparse + offload pressure above it. Arrival rate is
        low — long prompts saturate slots, and an open-loop stream that
        arrives faster than prefill drains measures only the queue.
        Output budgets stay modest (summarization shape: huge context
        in, short answer out). Tests override geometry down to fit
        tiny-engine max_len; the defaults fit the serve-bench engine."""
        params = dict(
            arrival="poisson",
            rate=1.0,
            n_requests=32,
            prompt_dist="lognormal",
            prompt_mean=8192,
            prompt_sigma=1.4,
            prompt_min=512,
            prompt_max=65536,
            phrase_len=16,
            output_dist="lognormal",
            output_mean=128,
            output_sigma=0.5,
            output_min=16,
            output_max=512,
        )
        params.update(overrides)
        return cls(**params)

    @classmethod
    def mixed_tenants(cls, tenants=("tenant_a", "tenant_b"), seed=0,
                      interactive_rate=4.0, interactive_n=16,
                      batch_rate=8.0, batch_ramp_from=1.0, batch_n=16,
                      interactive_overrides=None, batch_overrides=None,
                      **common):
        """The front-door acceptance workload: per tenant, an
        INTERACTIVE Poisson stream (steady chat-shaped arrivals) plus a
        BATCH ramp (offered load climbing from ``batch_ramp_from`` to
        ``batch_rate`` — by the tail of the run batch alone saturates
        the target, which is exactly when the interactive TTFT budget
        is earned or lost). Returns a MixedWorkload whose ``requests()``
        merges every sub-stream arrival-sorted with each row tagged
        ``priority``/``tenant``.

        Determinism: each sub-spec's seed derives from (``seed``, tenant
        index, class) by fixed arithmetic — same seed, same tenants,
        same streams, forever. ``common`` overrides apply to every
        sub-spec (geometry knobs: prompt/output bounds, vocab);
        ``interactive_overrides``/``batch_overrides`` apply per class."""
        parts = []
        for i, tenant in enumerate(tenants):
            ikw = dict(
                arrival="poisson", rate=interactive_rate,
                n_requests=interactive_n,
                seed=seed * 1000 + i * 2 + 1)
            ikw.update(common)
            ikw.update(interactive_overrides or {})
            parts.append((tenant, "interactive", cls(**ikw)))
            bkw = dict(
                arrival="ramp", rate=batch_rate,
                ramp_from=batch_ramp_from, n_requests=batch_n,
                seed=seed * 1000 + i * 2 + 2)
            bkw.update(common)
            bkw.update(batch_overrides or {})
            parts.append((tenant, "batch", cls(**bkw)))
        return MixedWorkload(parts, seed=seed)


@dataclasses.dataclass(frozen=True)
class MixedWorkload:
    """Several tagged WorkloadSpec sub-streams merged into one arrival-
    sorted stream. Duck-types the WorkloadSpec surface the runner and
    report use (``requests()``, ``to_json()``, ``seed``)."""

    parts: tuple   # ((tenant, priority, WorkloadSpec), ...)
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(self, "parts", tuple(self.parts))
        if not self.parts:
            raise ValueError("MixedWorkload needs at least one part")

    def requests(self):
        rows = []
        for tenant, priority, spec in self.parts:
            for r in spec.requests():
                r.priority = priority
                r.tenant = tenant
                rows.append(r)
        rows.sort(key=lambda r: r.arrival_s)
        return rows

    def to_json(self):
        return {
            "mixed_tenants": [
                {"tenant": tenant, "priority": priority,
                 "spec": spec.to_json()}
                for tenant, priority, spec in self.parts],
            "seed": self.seed,
        }


# ------------------------------------------------------------------ trace


def save_trace(requests, path):
    """Write a request stream as replayable JSONL — one object per
    request with explicit token ids, so replay is exact."""
    with open(path, "w") as f:
        for r in requests:
            f.write(json.dumps({
                "arrival_s": r.arrival_s,
                "prompt": [int(t) for t in np.asarray(r.prompt)],
                "max_new_tokens": int(r.max_new_tokens),
                "temperature": float(r.temperature),
                "seed": int(r.seed),
            }))
            f.write("\n")
    return path


def replay_trace(path, vocab_size=50257, seed=0):
    """Load a JSONL trace into LoadRequest rows, arrival-sorted.

    Each line needs ``arrival_s`` plus either ``prompt`` (explicit token
    ids — exact replay) or ``prompt_len`` (tokens synthesized
    deterministically from ``seed`` + line order, for traces captured
    from systems that log lengths but not content). ``max_new_tokens``
    defaults to 16; ``temperature``/``seed`` default to 0/line index.
    """
    rng = np.random.RandomState(seed)
    reqs = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if "prompt" in row:
                toks = np.asarray(row["prompt"], np.int32)
            elif "prompt_len" in row:
                toks = rng.randint(0, vocab_size,
                                   size=(int(row["prompt_len"]),)
                                   ).astype(np.int32)
            else:
                raise ValueError(
                    "trace line {} has neither 'prompt' nor 'prompt_len'"
                    .format(i + 1))
            if toks.size < 1:
                raise ValueError("trace line {} has an empty prompt"
                                 .format(i + 1))
            reqs.append(LoadRequest(
                arrival_s=float(row["arrival_s"]),
                prompt=toks,
                max_new_tokens=int(row.get("max_new_tokens", 16)),
                temperature=float(row.get("temperature", 0.0)),
                seed=int(row.get("seed", i))))
    reqs.sort(key=lambda r: r.arrival_s)
    return reqs
