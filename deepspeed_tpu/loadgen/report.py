"""Sustained-load report: windowed SLO time-series, saturation sweep,
noise-aware regression gate.

The report is the harness's one artifact — a plain-JSON document that
carries (a) the per-window curves (TTFT/ITL p50/p99, queue depth, slot
occupancy, tokens/sec) the collector recorded, (b) run aggregates and
the SLO/goodput verdict, and (c) enough provenance (workload echo,
platform, schema version) that two reports can be compared honestly.

The regression gate is NOISE-AWARE because a fixed threshold is wrong
at both ends: tight enough to catch real 10% regressions, it flags
run-to-run noise every week; loose enough to never false-alarm, it
waves through real slowdowns. The windowed time-series is what breaks
the dilemma — each report carries N per-window measurements of every
metric, so the gate can estimate each run's OWN noise (standard error
of the window mean) and demand the A/B delta clear both a relative
floor and k standard errors of the combined noise. An A/A comparison
(same report twice) has delta exactly 0 and always passes; a real 2x
TTFT regression clears any plausible noise floor and fails — both ends
are pinned by tests/unit/test_loadgen.py.
"""

import math

from deepspeed_tpu.loadgen import slo as slo_mod

SCHEMA_VERSION = 7  # v2: + chaos section (recovery/requests_lost) and
# per-sample terminal phase. v3: + prefix section (hit rate, bytes
# shipped by cross-replica adoption, affinity-routed count). v4: +
# disagg section (prefill->decode handoff counts, fallbacks, bytes
# shipped). v5: + frontdoor section (per-class SLO attainment, sheds by
# reason, per-tenant tallies, preemption counts) and per-sample
# priority/tenant/shed_reason keys. v6: + adapter section (which
# ModelAdapter served the run, MoE expert-load balance, the sparse-
# attention token fraction, offloaded-page counts). v7: + paged section
# (page-granular KV pool facts: page quantum, arena size, peak pages
# in use, utilization at peak) — each additive, but comparisons across
# versions deserve the gate's schema caveat.

# Gate polarity: which direction is a REGRESSION for each report
# metric. Lower-is-better latencies only fail when they grow;
# higher-is-better rates only fail when they shrink — an improvement
# must never fail a gate (that trains people to stop running it).
LOWER_IS_BETTER = ("ttft_p50_ms", "ttft_p99_ms", "itl_p50_ms",
                   "itl_p99_ms", "queue_wait_p99_ms")
HIGHER_IS_BETTER = ("tokens_per_sec", "goodput_tokens_per_sec",
                    "goodput_tokens_per_sec_per_chip", "slo_attainment")
GATE_DEFAULT_METRICS = ("ttft_p99_ms", "itl_p99_ms", "tokens_per_sec",
                        "goodput_tokens_per_sec")

# window-metric key -> (registry snapshot key, histogram stat, scale)
_WINDOW_HIST = {
    "ttft_p50_ms": ("ttft_seconds", "p50", 1e3),
    "ttft_p99_ms": ("ttft_seconds", "p99", 1e3),
    "itl_p50_ms": ("inter_token_seconds", "p50", 1e3),
    "itl_p99_ms": ("inter_token_seconds", "p99", 1e3),
    "queue_wait_p50_ms": ("queue_wait_seconds", "p50", 1e3),
    "queue_wait_p99_ms": ("queue_wait_seconds", "p99", 1e3),
}
_WINDOW_GAUGE = {
    "queue_depth": "queue_depth",
    "slot_occupancy": "slot_occupancy",
}


def _window_rows(windows, t0):
    """Flatten collector records into the report's window rows: stable
    top-level keys (the schema the gate and the docs promise), times
    relative to the run start."""
    rows = []
    for w in windows:
        m = w["metrics"]
        row = {
            "index": w["index"],
            "t_start_s": round(w["t_start"] - t0, 6),
            "duration_s": round(w["duration_s"], 6),
        }
        for key, (src, stat, scale) in _WINDOW_HIST.items():
            stats = m.get(src)
            v = stats.get(stat) if isinstance(stats, dict) else None
            row[key] = None if v is None else v * scale
        for key, src in _WINDOW_GAUGE.items():
            row[key] = m.get(src)
        toks = m.get("tokens_out", 0) or 0
        row["tokens_out"] = int(toks)
        row["tokens_per_sec"] = toks / w["duration_s"]
        row["requests_completed"] = int(m.get("requests_completed", 0) or 0)
        rows.append(row)
    return rows


def _percentile(vals, p):
    if not vals:
        return None
    s = sorted(vals)
    return s[min(int(len(s) * p / 100.0), len(s) - 1)]


def _chaos_section(result, slo):
    """Fold the run's recovery facts into the report's ``chaos``
    section. Present on every report (stable schema — a fault-free run
    shows zeros), load-bearing on chaos runs: ``requests_lost`` is the
    recovery invariant's bottom line (MUST be 0), ``recovery_time_s``
    the total wall clock spent rebuilding, and the attainment split —
    requests whose lifespan overlapped a recovery interval vs the rest —
    is the SLO price of surviving the fault, separated from steady-state
    quality instead of smeared over the whole run."""
    recovery = list(getattr(result, "recovery", []) or [])
    touched, untouched = [], []
    for s in result.samples:
        if s["shed"] or s["e2e_s"] is None:
            continue
        start, end = s["arrival_s"], s["arrival_s"] + s["e2e_s"]
        hit = any(start <= r["t_end_s"] and end >= r["t_start_s"]
                  for r in recovery)
        (touched if hit else untouched).append(s)

    def _att(rows):
        if not rows:
            return None
        return sum(1 for s in rows if slo.meets(s)) / len(rows)

    return {
        "requests_lost": int(getattr(result, "requests_lost", 0)),
        "faults_injected": int(getattr(result, "faults_injected", 0)),
        "recoveries": len(recovery),
        "recovery_time_s": round(sum(r["duration_s"] for r in recovery), 6),
        "recovery_intervals": recovery,
        "requests_during_recovery": len(touched),
        "slo_attainment_during_recovery": _att(touched),
        "slo_attainment_outside_recovery": _att(untouched),
    }


def _prefix_section(result):
    """Prefix-cache facts for the run (stable schema — an engine with
    no prefix cache shows zeros and a null hit rate). The counters are
    run DELTAS the runner read back; ``hit_rate`` is the headline the
    fleet-affinity A/B compares: hits / probes, null when the run never
    probed (so a disabled cache is distinguishable from a 0% one)."""
    hits = int(getattr(result, "prefix_hits", 0))
    misses = int(getattr(result, "prefix_misses", 0))
    probes = hits + misses
    return {
        "prefix_hits": hits,
        "prefix_misses": misses,
        "hit_rate": (hits / probes) if probes else None,
        "prefix_bytes_shipped": int(
            getattr(result, "prefix_bytes_shipped", 0)),
        "affinity_routed": int(getattr(result, "affinity_routed", 0)),
    }


def _disagg_section(result):
    """Disaggregated-serving facts for the run (stable schema — a
    single engine or all-mixed fleet shows zeros). The counters are run
    DELTAS the runner read back: ``handoffs`` prompts captured off
    prefill replicas and migrated, ``handoff_fallbacks`` the re-prefills
    taken when no decode-capable replica could adopt (each one is a
    resilience event, not a loss — the stream still completed), and the
    KV bytes the handoff records shipped host-side. The disagg A/B's
    headline lives in the aggregate ITL percentiles; this section is
    the attribution that the traffic really migrated."""
    return {
        "handoffs": int(getattr(result, "handoffs", 0)),
        "handoff_fallbacks": int(getattr(result, "handoff_fallbacks", 0)),
        "handoff_bytes_shipped": int(
            getattr(result, "handoff_bytes_shipped", 0)),
    }


def _frontdoor_section(result, slo, class_slos=None):
    """Front-door facts for the run (stable schema — an untagged run
    shows one ``untagged`` class and zero preemptions). Samples group
    by their ``priority`` tag; each class is judged against its OWN
    budget from ``class_slos`` (name -> SLO) with the run-level SLO as
    the fallback — per-class attainment under per-class budgets is the
    number the mixed-tenant acceptance gate pins. ``sheds_by_reason``
    folds the structured QueueFull reasons (rate_limit /
    frontdoor_full / deadline / slo / queue_full); preemption counts
    are the runner's counter deltas."""
    class_slos = class_slos or {}
    by_class = {}
    for s in result.samples:
        by_class.setdefault(s.get("priority") or "untagged",
                            []).append(s)
    classes = {}
    for cname, rows in sorted(by_class.items()):
        budget = class_slos.get(cname, slo)
        ttfts = [r["ttft_s"] * 1e3 for r in rows
                 if r.get("ttft_s") is not None]
        itls = [r["itl_s"] * 1e3 for r in rows
                if r.get("itl_s") is not None]
        classes[cname] = {
            "requests": len(rows),
            "completed": sum(1 for r in rows if r["completed"]),
            "shed": sum(1 for r in rows if r["shed"]),
            "budgets": budget.to_json(),
            "slo_attainment": (sum(1 for r in rows if budget.meets(r))
                               / len(rows)) if rows else None,
            "ttft_p50_ms": _percentile(ttfts, 50),
            "ttft_p99_ms": _percentile(ttfts, 99),
            "itl_p50_ms": _percentile(itls, 50),
            "itl_p99_ms": _percentile(itls, 99),
        }
    sheds = {}
    tenants = {}
    for s in result.samples:
        if s["shed"]:
            reason = s.get("shed_reason") or "queue_full"
            sheds[reason] = sheds.get(reason, 0) + 1
        tname = s.get("tenant")
        if tname is not None:
            row = tenants.setdefault(
                tname, {"requests": 0, "completed": 0, "shed": 0,
                        "tokens_out": 0})
            row["requests"] += 1
            row["completed"] += 1 if s["completed"] else 0
            row["shed"] += 1 if s["shed"] else 0
            row["tokens_out"] += s["tokens_out"]
    return {
        "classes": classes,
        "sheds_by_reason": sheds,
        "tenants": tenants,
        "preemptions": int(getattr(result, "preemptions", 0)),
        "preempt_resumes": int(getattr(result, "preempt_resumes", 0)),
    }


def _adapter_section(result):
    """Adapter facts for the run (stable schema — a plain GPT-2 run
    shows the adapter name with empty/zero workload tallies). MoE:
    per-expert dispatch totals plus the imbalance ratio (max load over
    uniform share; 1.0 = perfectly balanced) the expert-parallel A/B
    reads. Long-context: the sparse threshold in force and the fraction
    of GENERATED tokens emitted from query positions past it — computed
    from the per-sample geometry, so it is exact for the stream the run
    actually served — plus the KV host-offload swap deltas
    (offloaded/restored page counts) that evidence capacity headroom
    came from the hierarchy, not luck."""
    load = [float(v) for v in getattr(result, "expert_load", []) or []]
    total = sum(load)
    thr = int(getattr(result, "sparse_decode_threshold", 0) or 0)
    gen = sparse = 0
    if thr > 0:
        for s in result.samples:
            n = s["tokens_out"]
            if not n:
                continue
            gen += n
            # Generated tokens sit at positions prompt..prompt+n-1; a
            # token is sparse-served when its position >= threshold.
            sparse += max(0, s["prompt_tokens"] + n - max(
                thr, s["prompt_tokens"]))
    return {
        "adapter": getattr(result, "adapter", None),
        "expert_load": load,
        "expert_load_imbalance": (
            max(load) * len(load) / total) if total else None,
        "sparse_decode_threshold": thr,
        "sparse_token_fraction": (sparse / gen) if gen else None,
        "offloaded_pages": int(getattr(result, "swap_outs", 0)),
        "restored_pages": int(getattr(result, "swap_ins", 0)),
    }


def _paged_section(result):
    """Paged-KV facts for the run (schema v7; stable schema — a dense
    engine shows ``paged: false`` with zero/null tallies). The numbers
    are the runner's poll of ``engine.kv_page_stats()``: the page
    quantum and arena size are static, ``pages_peak`` is the high-water
    page count across the run's steps, and ``page_utilization`` is
    live tokens over mapped capacity AT that peak — the fragmentation
    bound that says how much of the claimed HBM actually held KV."""
    util = getattr(result, "kv_page_utilization", None)
    return {
        "paged": bool(getattr(result, "paged", False)),
        "page_len": int(getattr(result, "kv_page_len", 0) or 0),
        "pages_total": int(getattr(result, "kv_pages_total", 0) or 0),
        "pages_peak": int(getattr(result, "kv_pages_peak", 0) or 0),
        "page_utilization": None if util is None else round(float(util), 6),
    }


def build_report(spec, result, slo, chips=1, platform=None, extra=None,
                 class_slos=None):
    """Fold one RunResult into the report document.

    Aggregates come from the per-request samples (exact, not windowed);
    the ``windows`` rows carry the curves. ``extra`` merges caller
    provenance (git hash, config digest, probe state) into
    ``context`` — the gate reads context to warn when two reports were
    never comparable to begin with. ``class_slos`` (name -> SLO) gives
    each priority class its own budget in the frontdoor section."""
    t0 = result.windows[0]["t_start"] if result.windows else 0.0
    ttfts = [s["ttft_s"] * 1e3 for s in result.samples
             if s["ttft_s"] is not None]
    itls = [s["itl_s"] * 1e3 for s in result.samples
            if s["itl_s"] is not None]
    wall = max(result.wall_s, 1e-9)
    slo_section = slo_mod.evaluate(result.samples, slo, result.wall_s,
                                   chips=chips)
    context = {"platform": platform, "chips": int(chips),
               "seed": getattr(spec, "seed", None)}
    if extra:
        context.update(extra)
    return {
        "schema_version": SCHEMA_VERSION,
        "workload": spec.to_json() if hasattr(spec, "to_json") else None,
        "context": context,
        "aggregate": {
            "wall_s": result.wall_s,
            "submitted": result.submitted,
            "completed": result.completed,
            "shed": result.shed,
            "tokens_out": result.tokens_out,
            "tokens_per_sec": result.tokens_out / wall,
            "ttft_p50_ms": _percentile(ttfts, 50),
            "ttft_p99_ms": _percentile(ttfts, 99),
            "itl_p50_ms": _percentile(itls, 50),
            "itl_p99_ms": _percentile(itls, 99),
            "slo_attainment": slo_section["attainment"],
            "goodput_tokens_per_sec":
                slo_section["goodput_tokens_per_sec"],
            "goodput_tokens_per_sec_per_chip":
                slo_section["goodput_tokens_per_sec_per_chip"],
        },
        "slo": slo_section,
        "chaos": _chaos_section(result, slo),
        "prefix": _prefix_section(result),
        "disagg": _disagg_section(result),
        "frontdoor": _frontdoor_section(result, slo, class_slos),
        "adapter": _adapter_section(result),
        "paged": _paged_section(result),
        "timeseries": {
            "window_seconds": result.collector.window_seconds,
            "windows_total": result.collector._idx,
            "dropped": result.collector.dropped,
            "windows": _window_rows(result.windows, t0),
        },
        "samples": result.samples,
    }


# ------------------------------------------------------------- saturation


def saturation_sweep(run_fn, rates, attainment_floor=0.95):
    """Step the offered arrival rate through ``rates`` and report the
    max sustainable one.

    ``run_fn(rate)`` runs one sustained pass at that offered rate and
    returns its report (callers reuse ONE warm engine across steps —
    the sweep measures capacity, not compile time). A rate is
    SUSTAINABLE when SLO attainment held ``attainment_floor``; the knee
    where attainment collapses and tokens/sec flatlines is the
    engine's real capacity — the number a single-rate run can't give
    you."""
    steps = []
    max_rate = None
    for rate in rates:
        rep = run_fn(rate)
        att = rep["aggregate"]["slo_attainment"]
        ok = att is not None and att >= attainment_floor
        steps.append({
            "rate": rate,
            "attainment": att,
            "tokens_per_sec": rep["aggregate"]["tokens_per_sec"],
            "goodput_tokens_per_sec":
                rep["aggregate"]["goodput_tokens_per_sec"],
            "shed": rep["aggregate"]["shed"],
            "sustainable": ok,
        })
        if ok and (max_rate is None or rate > max_rate):
            max_rate = rate
    return {"attainment_floor": attainment_floor, "rates": steps,
            "max_sustainable_rate": max_rate}


# ------------------------------------------------------------------- gate


def _series(report, metric):
    """Per-window series for ``metric``: the windowed samples the noise
    floor is estimated from. Rate/goodput metrics don't have window
    rows under those exact names — tokens_per_sec does, and the
    goodput/attainment family falls back to it as its noise proxy (same
    underlying token stream)."""
    key = metric if metric in ("ttft_p50_ms", "ttft_p99_ms", "itl_p50_ms",
                               "itl_p99_ms", "queue_wait_p50_ms",
                               "queue_wait_p99_ms", "queue_depth",
                               "slot_occupancy", "tokens_per_sec") \
        else "tokens_per_sec"
    vals = [w.get(key) for w in report["timeseries"]["windows"]]
    return [v for v in vals if v is not None]


def _rel_sem(series, center):
    """Relative standard error of the window mean — this run's own
    noise, in the same units as a relative delta. Fewer than 2 windows
    (or a zero center) estimates nothing: returns 0, leaving the fixed
    ``rel_tol`` floor in charge."""
    n = len(series)
    if n < 2 or not center:
        return 0.0
    mean = sum(series) / n
    var = sum((v - mean) ** 2 for v in series) / (n - 1)
    return math.sqrt(var / n) / abs(center)


def _agg(report, metric):
    if metric == "slo_attainment":
        return report["aggregate"]["slo_attainment"]
    return report["aggregate"].get(metric)


def regression_gate(baseline, candidate, metrics=None, rel_tol=0.10,
                    noise_k=3.0):
    """Noise-aware A/B gate between two reports.

    Per metric: relative delta of the aggregate values, compared
    against ``threshold = max(rel_tol, noise_k * sqrt(sem_a^2 +
    sem_b^2))`` where each sem is that run's relative standard error
    estimated from its per-window series. A metric FLAGS only when the
    delta exceeds the threshold IN THE REGRESSION DIRECTION for its
    polarity — improvements never flag. Identical reports (A/A) have
    delta 0 and pass by construction.

    ``caveats`` lists context mismatches (platform, seed, schema) that
    make the comparison itself suspect — the gate still runs, but a
    red result on mismatched context blames the context first."""
    metrics = list(metrics or GATE_DEFAULT_METRICS)
    caveats = []
    for k in ("platform", "seed"):
        a = baseline.get("context", {}).get(k)
        b = candidate.get("context", {}).get(k)
        if a != b:
            caveats.append("context.{} differs: {!r} vs {!r}".format(
                k, a, b))
    if baseline.get("schema_version") != candidate.get("schema_version"):
        caveats.append("schema_version differs: {!r} vs {!r}".format(
            baseline.get("schema_version"),
            candidate.get("schema_version")))
    rows = {}
    for m in metrics:
        a, b = _agg(baseline, m), _agg(candidate, m)
        row = {"baseline": a, "candidate": b, "delta_rel": None,
               "noise_floor": None, "threshold": None,
               "direction": ("lower_is_better"
                             if m in LOWER_IS_BETTER else
                             "higher_is_better"),
               "flagged": False}
        if a is not None and b is not None and a != 0:
            delta = (b - a) / abs(a)
            noise = noise_k * math.sqrt(
                _rel_sem(_series(baseline, m), a) ** 2 +
                _rel_sem(_series(candidate, m), b) ** 2)
            thr = max(rel_tol, noise)
            regress = delta > thr if m in LOWER_IS_BETTER else delta < -thr
            row.update({"delta_rel": delta, "noise_floor": noise,
                        "threshold": thr, "flagged": bool(regress)})
        rows[m] = row
    out = {
        "pass": not any(r["flagged"] for r in rows.values()),
        "rel_tol": rel_tol,
        "noise_k": noise_k,
        "metrics": rows,
        "caveats": caveats,
    }
    # Cost-model arm (telemetry/xray.py): when both reports carry a
    # perf_xray section, compare the XLA cost models too — per-program
    # flops / bytes-accessed / predicted peak HBM and the bytes-per-
    # token total. These are COMPILER facts, not measurements: they are
    # deterministic per (program, shapes), so a CPU-only A/B catches a
    # "2x bytes per token" regression no timing series could resolve.
    # A/A compares a report against itself and passes by construction.
    xa, xb = baseline.get("perf_xray"), candidate.get("perf_xray")
    if xa is not None and xb is not None:
        from deepspeed_tpu.telemetry.xray import cost_model_gate

        xgate = cost_model_gate(xa, xb)
        out["perf_xray"] = xgate
        out["pass"] = out["pass"] and bool(xgate.get("pass", True))
    return out
