"""Version info for deepspeed_tpu.

Mirrors the surface of the reference's git_version_info
(/root/reference/deepspeed/git_version_info.py:1-17) without install-time codegen.
"""

version = "0.3.10+tpu.r1"
git_hash = "unknown"
git_branch = "main"
installed_ops = {}
compatible_ops = {}
