"""Version info for deepspeed_tpu.

Mirrors the reference's git_version_info (deepspeed/git_version_info.py:1-17):
prefer the install-time stamp written by setup.py, fall back to in-tree
defaults.
"""

try:
    from deepspeed_tpu.git_version_info_installed import (  # noqa: F401
        version, git_hash, git_branch)
except ImportError:
    version = "0.3.10+tpu.r1"
    git_hash = "unknown"
    git_branch = "main"

# Op status for ds_report parity (reference git_version_info.py keeps
# installed/compatible op dicts; ours are computed live by env_report).
installed_ops = {}
compatible_ops = {}
