"""BertSparseSelfAttention — BERT attention block with sparse attention core
(reference deepspeed/ops/sparse_attention/bert_sparse_self_attention.py:8-88).
"""

import dataclasses

import flax.linen as nn

from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig, SparsityConfig)


@dataclasses.dataclass
class BertConfigLike:
    """Minimal duck-typed stand-in for a HF/Bert config object."""
    hidden_size: int
    num_attention_heads: int


class BertSparseSelfAttention(nn.Module):
    """Q/K/V projections + SparseSelfAttention + head merge, the sparse twin
    of a BertSelfAttention layer.

    `config` needs `.hidden_size` and `.num_attention_heads` (same duck
    typing as the reference, bert_sparse_self_attention.py:36-44).
    """

    config: object = None
    sparsity_config: SparsityConfig = None

    def setup(self):
        cfg = self.config
        if cfg.hidden_size % cfg.num_attention_heads != 0:
            raise ValueError(
                "The hidden size (%d) is not a multiple of the number of "
                "attention heads (%d)" % (cfg.hidden_size,
                                          cfg.num_attention_heads))
        self.num_attention_heads = cfg.num_attention_heads
        self.attention_head_size = cfg.hidden_size // cfg.num_attention_heads
        self.all_head_size = (self.num_attention_heads *
                              self.attention_head_size)
        self.query = nn.Dense(self.all_head_size, name='query')
        self.key = nn.Dense(self.all_head_size, name='key')
        self.value = nn.Dense(self.all_head_size, name='value')
        sc = (self.sparsity_config if self.sparsity_config is not None
              else FixedSparsityConfig(num_heads=cfg.num_attention_heads))
        self.sparse_self_attention = SparseSelfAttention(sparsity_config=sc)

    def _transpose_for_scores(self, x):
        b, t, _ = x.shape
        x = x.reshape(b, t, self.num_attention_heads, self.attention_head_size)
        return x.transpose(0, 2, 1, 3)

    def __call__(self, hidden_states, attention_mask=None):
        q = self._transpose_for_scores(self.query(hidden_states))
        k = self._transpose_for_scores(self.key(hidden_states))
        v = self._transpose_for_scores(self.value(hidden_states))
        ctx = self.sparse_self_attention(q, k, v,
                                         key_padding_mask=attention_mask)
        b, h, t, d = ctx.shape
        return ctx.transpose(0, 2, 1, 3).reshape(b, t, self.all_head_size)
