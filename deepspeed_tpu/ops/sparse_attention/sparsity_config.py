"""Block-sparse attention layout configurations.

API-compatible with the reference's SparsityConfig hierarchy
(reference deepspeed/ops/sparse_attention/sparsity_config.py:9,63,94,243,421,544):
the same five patterns (Dense, Fixed, Variable, BigBird, BSLongformer) with the
same constructor parameters and the same `make_layout(seq_len) -> [num_heads,
num_blocks, num_blocks]` contract.

Implementation is new and TPU-shaped: layouts are built with vectorized numpy
index arithmetic (not per-element torch loops) because on TPU the layout is
*trace-time metadata* — it is lowered to a lookup table that steers a Pallas
kernel's grid (see kernels.py), never shipped to the device as a tensor.
"""

import numpy as np


class SparsityConfig:
    """Base class: shared block/head bookkeeping for all sparsity patterns.

    Arguments mirror the reference (sparsity_config.py:13-27):
      num_heads: attention heads in the layer.
      block: side of the square attention blocks (block x block).
      different_layout_per_head: if False (default) head 0's layout is
        propagated to all heads.
    """

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        """Zero-initialized [num_heads, num_blocks, num_blocks] layout."""
        if seq_len % self.block != 0:
            raise ValueError(
                'Sequence Length, {}, needs to be dividable by Block size {}!'
                .format(seq_len, self.block))
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """Degenerate all-ones layout — dense attention expressed in the
    block-sparse machinery (reference sparsity_config.py:63-91)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer style fixed pattern: dense local windows of
    `num_local_blocks`, plus per-window global representative column blocks
    (reference sparsity_config.py:94-240; Child et al. 2019)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_local_blocks=4,
                 num_global_blocks=1,
                 attention='bidirectional',
                 horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                'Number of blocks in a local window, {}, must be dividable by '
                'number of global blocks, {}!'.format(num_local_blocks,
                                                      num_global_blocks))
        self.num_global_blocks = num_global_blocks
        if attention not in ('unidirectional', 'bidirectional'):
            raise NotImplementedError(
                'only "uni/bi-directional" attentions are supported for now!')
        self.attention = attention
        if attention != 'bidirectional' and horizontal_global_attention:
            raise ValueError(
                'only "bi-directional" attentions can support horizontal '
                'global attention!')
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                'Number of different layouts cannot be more than one when you '
                'have set a single layout for all heads! Set '
                'different_layout_per_head to True.')
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                'Number of layout versions (num_different_global_patterns), '
                '{}, cannot be larger than number of local window blocks '
                'divided by number of global blocks, {} / {} = {}!'.format(
                    num_different_global_patterns, num_local_blocks,
                    num_global_blocks, num_local_blocks // num_global_blocks))
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        """Dense (or, for unidirectional, lower-triangular) block windows of
        num_local_blocks along the diagonal."""
        num_blocks = layout.shape[1]
        row = np.arange(num_blocks)[:, None]
        col = np.arange(num_blocks)[None, :]
        same_window = (row // self.num_local_blocks) == (col // self.num_local_blocks)
        if self.attention == 'unidirectional':
            same_window &= col <= row
        layout[h][same_window] = 1
        return layout

    def set_global_layout(self, h, layout):
        """Column-global blocks: in each local window the representative block
        (last minus h-dependent offset) is attended by all following rows
        (bidirectional: all rows). horizontal_global_attention mirrors the
        stripe across the row too."""
        num_blocks = layout.shape[1]
        first_global = self.num_local_blocks - (
            1 + h % self.num_different_global_patterns) * self.num_global_blocks

        end = num_blocks - (num_blocks % self.num_local_blocks)
        starts = list(range(first_global, end, self.num_local_blocks))
        # Possible short last window: clamp its global block into range.
        if end < num_blocks:
            starts.append(min(end + first_global,
                              num_blocks - self.num_global_blocks))
        for i in starts:
            first_row = 0 if self.attention == 'bidirectional' else i
            layout[h, first_row:, i:i + self.num_global_blocks] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + self.num_global_blocks, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Fixed pattern generalized: random blocks, variable-size local windows,
    explicit global block index lists (reference sparsity_config.py:243-418)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=0,
                 local_window_blocks=None,
                 global_block_indices=None,
                 global_block_end_indices=None,
                 attention='bidirectional',
                 horizontal_global_attention=False,
                 seed=None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = (local_window_blocks
                                    if local_window_blocks is not None else [4])
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    'Global block start indices length, {}, must be same as '
                    'global block end indices length, {}!'.format(
                        len(self.global_block_indices),
                        len(global_block_end_indices)))
            for start_idx, end_idx in zip(self.global_block_indices,
                                          global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(
                        'Global block start index, {}, must be smaller than '
                        'global block end index, {}!'.format(start_idx, end_idx))
        self.global_block_end_indices = global_block_end_indices
        if attention not in ('unidirectional', 'bidirectional'):
            raise NotImplementedError(
                'only "uni/bi-directional" attentions are supported for now!')
        self.attention = attention
        if attention != 'bidirectional' and horizontal_global_attention:
            raise ValueError(
                'only "bi-directional" attentions can support horizontal '
                'global attention!')
        self.horizontal_global_attention = horizontal_global_attention
        # Unlike the reference (which consumes python's global `random`), the
        # random pattern is seedable so layouts are reproducible trace-time
        # constants — required for jit cache stability across processes.
        # seed=None still gets ONE concrete, PROCESS-INDEPENDENT seed:
        # default_rng(None) would draw fresh entropy per call (breaking the
        # repeated-make_layout invariant) and per process (every host must
        # trace the SAME layout — divergent patterns with allreduced grads
        # would silently corrupt multi-host training).
        self._seed = seed if seed is not None else 0x5eed
        self._rng = np.random.default_rng(self._seed)

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                'Number of random blocks, {}, must be smaller than overal '
                'number of blocks in a row, {}!'.format(self.num_random_blocks,
                                                        num_blocks))
        for row in range(num_blocks):
            rnd_cols = self._rng.choice(num_blocks, self.num_random_blocks,
                                        replace=False)
            layout[h, row, rnd_cols] = 1
        return layout

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        start = 0
        block_size = self.local_window_blocks[-1]
        for size in self.local_window_blocks:
            end = min(start + size, num_blocks)
            self._fill_window(h, layout, start, end)
            start += size
        # Remaining sequence: repeat the last window size.
        while start < num_blocks:
            end = min(start + block_size, num_blocks)
            self._fill_window(h, layout, start, end)
            start += block_size
        return layout

    def _fill_window(self, h, layout, start, end):
        if start >= end:
            return
        n = end - start
        row = np.arange(n)[:, None]
        col = np.arange(n)[None, :]
        keep = col <= row if self.attention == 'unidirectional' else np.ones(
            (n, n), dtype=bool)
        layout[h, start:end, start:end][keep] = 1

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start_idx, end_idx in spans:
            if start_idx >= num_blocks:
                continue
            end_idx = min(end_idx, num_blocks)
            if self.horizontal_global_attention:
                layout[h, start_idx:end_idx, :] = 1
            first_row = 0 if self.attention == 'bidirectional' else start_idx
            layout[h, first_row:, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len):
        # Reseed per call: repeated make_layout on one config must yield the
        # SAME layout (callers treat the layout as a pure function of the
        # config; a stateful rng would silently diverge between calls).
        self._rng = np.random.default_rng(self._seed)
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird ITC pattern: random + sliding window + leading global blocks
    (reference sparsity_config.py:421-541; Zaheer et al. 2020)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=1,
                 num_sliding_window_blocks=3,
                 num_global_blocks=1,
                 seed=None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        # Process-independent default seed (see VariableSparsityConfig).
        self._seed = seed if seed is not None else 0x5eed
        self._rng = np.random.default_rng(self._seed)

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                'Number of random blocks, {}, must be smaller than overal '
                'number of blocks in a row, {}!'.format(self.num_random_blocks,
                                                        num_blocks))
        for row in range(num_blocks):
            rnd_cols = self._rng.choice(num_blocks, self.num_random_blocks,
                                        replace=False)
            layout[h, row, rnd_cols] = 1
        return layout

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                'Number of sliding window blocks, {}, must be smaller than '
                'overal number of blocks in a row, {}!'.format(
                    self.num_sliding_window_blocks, num_blocks))
        w = self.num_sliding_window_blocks // 2
        row = np.arange(num_blocks)[:, None]
        col = np.arange(num_blocks)[None, :]
        layout[h][np.abs(row - col) <= w] = 1
        return layout

    def set_global_layout_itc(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_global_blocks:
            raise ValueError(
                'Number of global blocks, {}, must be smaller than overal '
                'number of blocks in a row, {}!'.format(self.num_global_blocks,
                                                        num_blocks))
        layout[h, :self.num_global_blocks, :] = 1
        layout[h, :, :self.num_global_blocks] = 1
        return layout

    def make_layout(self, seq_len):
        # Reseed per call so repeated layouts are identical (see
        # VariableSparsityConfig.make_layout).
        self._rng = np.random.default_rng(self._seed)
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + symmetric global row/column
    stripes at given block indices (reference sparsity_config.py:544-669)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_sliding_window_blocks=3,
                 global_block_indices=None,
                 global_block_end_indices=None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    'Global block start indices length, {}, must be same as '
                    'global block end indices length, {}!'.format(
                        len(self.global_block_indices),
                        len(global_block_end_indices)))
            for start_idx, end_idx in zip(self.global_block_indices,
                                          global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(
                        'Global block start index, {}, must be smaller than '
                        'global block end index, {}!'.format(start_idx, end_idx))
        self.global_block_end_indices = global_block_end_indices

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                'Number of sliding window blocks, {}, must be smaller than '
                'overal number of blocks in a row, {}!'.format(
                    self.num_sliding_window_blocks, num_blocks))
        w = self.num_sliding_window_blocks // 2
        row = np.arange(num_blocks)[:, None]
        col = np.arange(num_blocks)[None, :]
        layout[h][np.abs(row - col) <= w] = 1
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start_idx, end_idx in spans:
            if start_idx >= num_blocks:
                continue
            end_idx = min(end_idx, num_blocks)
            layout[h, start_idx:end_idx, :] = 1
            layout[h, :, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)
