"""Block-sparse flash attention — the TPU-native replacement for the
reference's Triton kernel trio (sdd matmul -> sparse softmax -> dsd matmul,
reference deepspeed/ops/sparse_attention/matmul.py:16-60, softmax.py:17-40)
and its OpenMP `sdd_segment` load balancer (csrc/sparse_attention/utils.cpp:119).

Design: the SparsityConfig layout [H, nb, nb] is compile-time metadata. It is
lowered (host-side, numpy) to a per-(head, query-block) lookup table of active
key-block indices, padded to the max row degree. One Pallas kernel then runs a
flash-style online-softmax sweep over *only the active blocks*: scores for a
block pair live in VMEM registers and the [T, T] matrix is never materialized.
This fuses the reference's three kernel launches (plus its block
gather/scatter) into a single MXU-resident kernel, and replaces the sdd_segment
load-balancing machinery entirely — the grid is naturally balanced because
every (head, q-block) program does max_degree iterations with inactive slots
masked (layouts produced by SparsityConfig have near-uniform row degree).

Backward follows the two-pass flash scheme: a dq kernel walks the same LUT; a
dk/dv kernel walks the *transposed* LUT (for each key block, the query blocks
that touch it), both recomputing probabilities from the saved logsumexp.

All kernels run in interpret mode off-TPU so the CPU test mesh exercises the
identical code path.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.transformer.kernels.attention import (
    _bwd_mode, _mxu_precision)

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


_LUT_OP = None  # lazily-loaded C++ lowering op (None until first use)


def _lut_op():
    """The C++ OpenMP LUT lowering (csrc/sparse_attention/lut.cpp — the
    reference's sdd_segment tier, csrc/sparse_attention/utils.cpp:119).
    Returns the bound cdll or False if unavailable."""
    global _LUT_OP
    if _LUT_OP is None:
        from deepspeed_tpu.op_builder import SparseLutBuilder
        builder = SparseLutBuilder()
        try:
            _LUT_OP = builder.load(verbose=False) \
                if builder.is_compatible() else False
        except (RuntimeError, OSError):
            _LUT_OP = False
    return _LUT_OP


def build_luts(layout):
    """Lower a [H, nb, nb] 0/1 layout to forward and transposed LUTs.

    Returns (fwd_lut [H, nb, max_deg], bwd_lut [H, nb, max_deg_t]) int32
    numpy arrays padded with -1. fwd_lut[h, i] lists the active key blocks for
    query block i; bwd_lut[h, j] lists the active query blocks for key block j.

    The lowering runs in the C++ OpenMP op when a toolchain is available
    (one parallel pass per direction); falls back to numpy loops otherwise.
    """
    layout = np.asarray(layout, dtype=bool)
    h, nb, _ = layout.shape

    op = _lut_op()
    if op:
        import ctypes
        lay32 = np.ascontiguousarray(layout, dtype=np.int32)
        ptr = lay32.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

        def lower(transpose):
            deg = int(op.ds_lut_max_degree(h, nb, nb, ptr, transpose))
            lut = np.empty((h, nb, deg), dtype=np.int32)
            op.ds_build_lut(h, nb, nb, ptr, transpose, deg,
                            lut.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            return lut

        return lower(0), lower(1)

    def rows_to_lut(mat):  # mat: [H, rows, cols] bool
        deg = mat.sum(-1).max() if mat.any() else 1
        deg = max(int(deg), 1)
        lut = np.full((h, mat.shape[1], deg), -1, dtype=np.int32)
        for hi in range(h):
            for r in range(mat.shape[1]):
                cols = np.nonzero(mat[hi, r])[0]
                lut[hi, r, :len(cols)] = cols
        return lut

    return rows_to_lut(layout), rows_to_lut(layout.transpose(0, 2, 1))


def _apply_masks(s, q_start, c, blk, kpm_blk, bias_blk, valid, causal,
                 kpm_mode, bias_mode):
    """Score post-processing shared by all kernels. s: [bq, blk] fp32."""
    if kpm_blk is not None:
        s = s * kpm_blk if kpm_mode == 'mul' else s + kpm_blk
    if bias_blk is not None:
        s = s * bias_blk if bias_mode == 'mul' else s + bias_blk
    if causal:
        bq = s.shape[0]
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, blk), 0)
        k_pos = c * blk + jax.lax.broadcasted_iota(jnp.int32, (bq, blk), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return jnp.where(valid, s, NEG_INF)


def _unpack(refs, n_out, has_kpm, has_bias):
    """Split the flat pallas ref list into (q, k, v, lut, kpm, bias, rest...)."""
    refs = list(refs)
    q_ref, k_ref, v_ref, lut_ref = refs[:4]
    idx = 4
    kpm_ref = bias_ref = None
    if has_kpm:
        kpm_ref = refs[idx]
        idx += 1
    if has_bias:
        bias_ref = refs[idx]
        idx += 1
    return q_ref, k_ref, v_ref, lut_ref, kpm_ref, bias_ref, refs[idx:]


def _fwd_kernel(*refs, scale, blk, causal, has_kpm, has_bias, kpm_mode,
                bias_mode, precision):
    (q_ref, k_ref, v_ref, lut_ref, kpm_ref, bias_ref,
     (o_ref, lse_ref)) = _unpack(refs, 2, has_kpm, has_bias)

    q = q_ref[0, 0].astype(jnp.float32) * scale            # [bq, d]
    bq, d = q.shape
    iq = pl.program_id(2)
    max_deg = lut_ref.shape[2]

    def body(j, carry):
        acc, m_prev, l_prev = carry
        col = lut_ref[0, 0, j]
        valid = col >= 0
        c = jnp.maximum(col, 0)
        k_blk = k_ref[0, 0, pl.ds(c * blk, blk)].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(c * blk, blk)].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=precision)
        kpm_blk = (kpm_ref[0, pl.ds(c * blk, blk)][None, :]
                   if kpm_ref is not None else None)
        bias_blk = (bias_ref[0, 0, :, pl.ds(c * blk, blk)]
                    if bias_ref is not None else None)
        s = _apply_masks(s, iq * bq, c, blk, kpm_blk, bias_blk, valid, causal,
                         kpm_mode, bias_mode)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Keep m finite when a whole block is masked (exp(-inf - -inf) traps).
        m_safe = jnp.maximum(m_new, 0.5 * NEG_INF)
        alpha = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        return acc, m_new, l_new

    acc, m, l = jax.lax.fori_loop(
        0, max_deg, body,
        (jnp.zeros((bq, d), jnp.float32),
         jnp.full((bq, 1), NEG_INF, jnp.float32),
         jnp.zeros((bq, 1), jnp.float32)))

    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.maximum(m, 0.5 * NEG_INF) + jnp.log(l)


def _recompute_p_ds(q, do, lse, delta, k_blk, v_blk, kpm_blk, bias_blk,
                    valid, q_start, c, blk, scale, causal, kpm_mode,
                    bias_mode, precision):
    """Shared backward block recompute for one (row, column) block pair:
    s is rebuilt exactly as the forward built it (same masks, same
    precision), then p = exp(s - lse) and ds = p * (dp - delta) * scale.
    In mul-mask modes the mask scales the pre-softmax score, so it also
    scales the score gradient ds flowing back to q/k. Used by all three
    backward kernels so the split and fused paths cannot diverge."""
    s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=precision) * scale
    s = _apply_masks(s, q_start, c, blk, kpm_blk, bias_blk, valid, causal,
                     kpm_mode, bias_mode)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32,
                             precision=precision)
    ds = p * (dp - delta) * scale
    if kpm_blk is not None and kpm_mode == 'mul':
        ds = ds * kpm_blk
    if bias_blk is not None and bias_mode == 'mul':
        ds = ds * bias_blk
    return p, ds


def _bwd_dq_kernel(*refs, scale, blk, causal, has_kpm, has_bias, kpm_mode,
                   bias_mode, precision):
    (q_ref, k_ref, v_ref, lut_ref, kpm_ref, bias_ref,
     (do_ref, lse_ref, delta_ref, dq_ref)) = _unpack(refs, 1, has_kpm, has_bias)

    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    bq, d = q.shape
    iq = pl.program_id(2)

    def body(j, dq):
        col = lut_ref[0, 0, j]
        valid = col >= 0
        c = jnp.maximum(col, 0)
        kv = pl.ds(c * blk, blk)
        k_blk = k_ref[0, 0, kv].astype(jnp.float32)
        v_blk = v_ref[0, 0, kv].astype(jnp.float32)
        kpm_blk = kpm_ref[0, kv][None, :] if kpm_ref is not None else None
        bias_blk = bias_ref[0, 0, :, kv] if bias_ref is not None else None
        _, ds = _recompute_p_ds(q, do, lse, delta, k_blk, v_blk, kpm_blk,
                                bias_blk, valid, iq * bq, c, blk, scale,
                                causal, kpm_mode, bias_mode, precision)
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32,
                                        precision=precision)

    dq = jax.lax.fori_loop(0, lut_ref.shape[2], body,
                           jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_fused_kernel(*refs, scale, blk, causal, has_kpm, has_bias,
                      kpm_mode, bias_mode, precision):
    """One-pass backward: dq, dk, dv from a single LUT-steered sweep.

    The split kernels each recompute s, p and dO.V^T per (row, column)
    block pair; this kernel computes them once, accumulating dk/dv into
    full-length fp32 VMEM scratch indexed by the forward LUT's column
    (a scatter — every listed pair is visited exactly once, so it covers
    exactly what the transposed-LUT gather covered; invalid entries alias
    column 0 but contribute exact zeros since their p and ds are zero).
    Same structure as the dense flash fused backward
    (ops/transformer/kernels/attention.py:_bwd_fused_kernel)."""
    (q_ref, k_ref, v_ref, lut_ref, kpm_ref, bias_ref,
     rest) = _unpack(refs, 3, has_kpm, has_bias)
    do_ref, lse_ref, delta_ref = rest[:3]
    dq_ref, dk_ref, dv_ref = rest[3:6]
    dk_acc, dv_acc = rest[6:8]

    i = pl.program_id(2)
    n_q = pl.num_programs(2)
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    bq, d = q.shape

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def body(j, dq):
        col = lut_ref[0, 0, j]
        valid = col >= 0
        c = jnp.maximum(col, 0)
        kv = pl.ds(c * blk, blk)
        k_blk = k_ref[0, 0, kv].astype(jnp.float32)
        v_blk = v_ref[0, 0, kv].astype(jnp.float32)
        kpm_blk = kpm_ref[0, kv][None, :] if kpm_ref is not None else None
        bias_blk = bias_ref[0, 0, :, kv] if bias_ref is not None else None
        p, ds = _recompute_p_ds(q, do, lse, delta, k_blk, v_blk, kpm_blk,
                                bias_blk, valid, i * bq, c, blk, scale,
                                causal, kpm_mode, bias_mode, precision)
        dv_acc[kv] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        dk_acc[kv] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32,
                                        precision=precision)

    dq = jax.lax.fori_loop(0, lut_ref.shape[2], body,
                           jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    @pl.when(i == n_q - 1)
    def _emit():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, blk, bq, causal, has_kpm, has_bias, kpm_mode,
                    bias_mode, precision):
    (q_ref, k_ref, v_ref, tlut_ref, kpm_ref, bias_ref,
     (do_ref, lse_ref, delta_ref, dk_ref, dv_ref)) = _unpack(
         refs, 2, has_kpm, has_bias)

    k_blk = k_ref[0, 0].astype(jnp.float32)                # [blk, d]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    d = k_blk.shape[1]
    jk = pl.program_id(2)
    kpm_blk = kpm_ref[0][None, :] if kpm_ref is not None else None  # [1, blk]

    def body(j, carry):
        dk, dv = carry
        row = tlut_ref[0, 0, j]
        valid = row >= 0
        r = jnp.maximum(row, 0)
        q = q_ref[0, 0, pl.ds(r * bq, bq)].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(r * bq, bq)].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(r * bq, bq)]
        delta = delta_ref[0, 0, pl.ds(r * bq, bq)]
        bias_blk = (bias_ref[0, 0, pl.ds(r * bq, bq), :]
                    if bias_ref is not None else None)
        p, ds = _recompute_p_ds(q, do, lse, delta, k_blk, v_blk, kpm_blk,
                                bias_blk, valid, r * bq, jk, blk, scale,
                                causal, kpm_mode, bias_mode, precision)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=precision)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=precision)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        0, tlut_ref.shape[2], body,
        (jnp.zeros((blk, d), jnp.float32), jnp.zeros((blk, d), jnp.float32)))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


@functools.lru_cache(maxsize=None)
def _sparse_fused_supported():
    """One-time probe for the SPARSE fused backward: its dk/dv scratch
    accumulation indexes VMEM by a LUT-loaded (data-dependent) offset —
    strictly harder for Mosaic than the dense fused kernel's loop-index
    offsets, so the dense probe (_fused_bwd_supported) does not cover it.
    On rejection, auto mode keeps the split kernels for sparse attention
    only. Off-TPU (interpret mode) the semantics are test-covered."""
    if jax.default_backend() != "tpu":
        return True
    # Force the fused path for the probe itself via _make_fn's force_bwd
    # parameter: attend_bwd consults this function on the auto path, so
    # probing through the public grad would otherwise recurse (and
    # mutating the DS_TPU_FLASH_BWD env var here would leak the forced
    # mode to concurrent traces on other threads).
    try:
        blk = 128
        layout = np.ones((1, 2, 2), np.int64)
        fwd_lut, bwd_lut = build_luts(layout)
        fn = _make_fn(fwd_lut, bwd_lut, blk, 1.0, False, False, False,
                      'add', 'add', precision=None, force_bwd="fused")
        q = jnp.zeros((1, 1, 2 * blk, 128), jnp.bfloat16)
        g = jax.grad(lambda q_: jnp.sum(
            fn(q_, q, q, None, None).astype(jnp.float32)))(q)
        jax.block_until_ready(g)
        return True
    except Exception as e:  # compile/verification failure — not data
        import warnings
        warnings.warn("fused sparse backward unsupported on this backend "
                      "({}); auto mode falls back to the split kernels"
                      .format(str(e)[:500]))
        return False


# ---------------------------------------------------------------------------
# custom_vjp assembly — one cached closure per (layout, flags) so the LUTs are
# baked into the jaxpr as constants (the layout is per-layer static metadata).
# ---------------------------------------------------------------------------

_FN_CACHE = {}


def _make_fn(fwd_lut, bwd_lut, blk, scale, causal, has_kpm, has_bias,
             kpm_mode, bias_mode, precision=None, force_bwd=None):
    # force_bwd pins the backward path ("fused"/"split") for this closure
    # regardless of DS_TPU_FLASH_BWD / the support probe — used by
    # _sparse_fused_supported so the probe never touches process state.
    # LUTs stay numpy in the closure; they are converted per call so that a
    # closure first built under a jit trace never caches tracer constants.
    fwd_lut = np.asarray(fwd_lut)
    bwd_lut = np.asarray(bwd_lut)
    flags = dict(causal=causal, has_kpm=has_kpm, has_bias=has_bias,
                 kpm_mode=kpm_mode, bias_mode=bias_mode, precision=precision)

    def fwd(q, k, v, kpm, bias):
        b, h, t, d = q.shape
        lut = jnp.asarray(fwd_lut)
        nq = t // blk
        grid = (b, h, nq)
        q_spec = pl.BlockSpec((1, 1, blk, d), lambda b_, h_, i: (b_, h_, i, 0))
        full = pl.BlockSpec((1, 1, t, d), lambda b_, h_, i: (b_, h_, 0, 0))
        lut_spec = pl.BlockSpec((1, 1, fwd_lut.shape[2]),
                                lambda b_, h_, i: (h_, i, 0))
        in_specs = [q_spec, full, full, lut_spec]
        args = [q, k, v, lut]
        if has_kpm:
            in_specs.append(pl.BlockSpec((1, t), lambda b_, h_, i: (b_, 0)))
            args.append(kpm.astype(jnp.float32))
        if has_bias:
            in_specs.append(pl.BlockSpec((1, 1, blk, t),
                                         lambda b_, h_, i: (b_, h_, i, 0)))
            args.append(bias.astype(jnp.float32))
        o, lse = pl.pallas_call(
            functools.partial(_fwd_kernel, scale=scale, blk=blk, **flags),
            grid=grid,
            in_specs=in_specs,
            out_specs=[q_spec,
                       pl.BlockSpec((1, 1, blk, 1),
                                    lambda b_, h_, i: (b_, h_, i, 0))],
            out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                       jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32)],
            interpret=_interpret(),
        )(*args)
        return o, lse

    @jax.custom_vjp
    def attend(q, k, v, kpm, bias):
        return fwd(q, k, v, kpm, bias)[0]

    def attend_fwd(q, k, v, kpm, bias):
        o, lse = fwd(q, k, v, kpm, bias)
        return o, (q, k, v, kpm, bias, o, lse)

    def attend_bwd(res, g):
        q, k, v, kpm, bias, o, lse = res
        b, h, t, d = q.shape
        lut = jnp.asarray(fwd_lut)
        tlut = jnp.asarray(bwd_lut)
        do = g
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)
        q_spec = pl.BlockSpec((1, 1, blk, d), lambda b_, h_, i: (b_, h_, i, 0))
        full = pl.BlockSpec((1, 1, t, d), lambda b_, h_, i: (b_, h_, 0, 0))
        row_blk = pl.BlockSpec((1, 1, blk, 1), lambda b_, h_, i: (b_, h_, i, 0))
        row_full = pl.BlockSpec((1, 1, t, 1), lambda b_, h_, i: (b_, h_, 0, 0))
        lut_spec = pl.BlockSpec((1, 1, fwd_lut.shape[2]),
                                lambda b_, h_, i: (h_, i, 0))

        in_specs = [q_spec, full, full, lut_spec]
        args = [q, k, v, lut]
        if has_kpm:
            in_specs.append(pl.BlockSpec((1, t), lambda b_, h_, i: (b_, 0)))
            args.append(kpm.astype(jnp.float32))
        if has_bias:
            in_specs.append(pl.BlockSpec((1, 1, blk, t),
                                         lambda b_, h_, i: (b_, h_, i, 0)))
            args.append(bias.astype(jnp.float32))
        in_specs += [q_spec, row_blk, row_blk]
        args += [do, lse, delta]

        if force_bwd:
            use_fused = force_bwd == "fused"
        else:
            use_fused = _bwd_mode(t, d, q.dtype) == "fused" and (
                os.environ.get("DS_TPU_FLASH_BWD") == "fused"
                or _sparse_fused_supported())
        if use_fused:
            # One LUT-steered sweep produces dq and scatter-accumulates
            # dk/dv into full-length fp32 scratch (same input layout as
            # the dq kernel, so the spec/arg lists are shared).
            from jax.experimental.pallas import tpu as pltpu

            dq, dk, dv = pl.pallas_call(
                functools.partial(_bwd_fused_kernel, scale=scale, blk=blk,
                                  **flags),
                grid=(b, h, t // blk),
                in_specs=in_specs,
                out_specs=[q_spec, full, full],
                out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                           jax.ShapeDtypeStruct(k.shape, k.dtype),
                           jax.ShapeDtypeStruct(v.shape, v.dtype)],
                scratch_shapes=[pltpu.VMEM((t, d), jnp.float32),
                                pltpu.VMEM((t, d), jnp.float32)],
                interpret=_interpret(),
            )(*args)
            return _finish_bwd(q, k, v, kpm, bias, do, lse, delta,
                               dq, dk, dv)

        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale, blk=blk, **flags),
            grid=(b, h, t // blk),
            in_specs=in_specs,
            out_specs=q_spec,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=_interpret(),
        )(*args)

        kv_spec = pl.BlockSpec((1, 1, blk, d), lambda b_, h_, j: (b_, h_, j, 0))
        tlut_spec = pl.BlockSpec((1, 1, bwd_lut.shape[2]),
                                 lambda b_, h_, j: (h_, j, 0))
        in_specs = [full, kv_spec, kv_spec, tlut_spec]
        args = [q, k, v, tlut]
        if has_kpm:
            in_specs.append(pl.BlockSpec((1, blk), lambda b_, h_, j: (b_, j)))
            args.append(kpm.astype(jnp.float32))
        if has_bias:
            in_specs.append(pl.BlockSpec((1, 1, t, blk),
                                         lambda b_, h_, j: (b_, h_, 0, j)))
            args.append(bias.astype(jnp.float32))
        in_specs += [full, row_full, row_full]
        args += [do, lse, delta]
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, scale=scale, blk=blk, bq=blk,
                              **flags),
            grid=(b, h, t // blk),
            in_specs=in_specs,
            out_specs=[kv_spec, kv_spec],
            out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)],
            interpret=_interpret(),
        )(*args)

        return _finish_bwd(q, k, v, kpm, bias, do, lse, delta, dq, dk, dv)

    def _finish_bwd(q, k, v, kpm, bias, do, lse, delta, dq, dk, dv):
        """Shared tail of both backward paths: mask/bias cotangents."""
        b, h, t, d = q.shape
        # The key-padding mask is an input mask, never a learned parameter:
        # its cotangent is defined as zero (documented non-differentiable).
        dkpm = None if kpm is None else jnp.zeros_like(kpm)
        # attn_bias CAN be learned (the reference's rpe receives real grads
        # under torch autograd), so its cotangent must be real: reconstruct
        # p and dS densely — the bias is already a dense [B,H,T,T] tensor,
        # so its gradient is inherently dense-sized and this costs two
        # einsums, comparable to one bwd kernel pass.
        dbias = None
        if bias is not None:
            f32 = jnp.float32
            # layout block mask (from the LUT: listed kv-block columns),
            # then the causal mask — matching _apply_masks exactly.
            nq = t // blk
            valid_blocks = np.zeros((h, nq, nq), bool)
            for h_ in range(h):
                for i_ in range(nq):
                    cols = fwd_lut[h_, i_]
                    valid_blocks[h_, i_, cols[cols >= 0]] = True
            valid_np = np.repeat(np.repeat(valid_blocks, blk, axis=1),
                                 blk, axis=2)
            if causal:
                pos = np.arange(t)
                valid_np = valid_np & (pos[:, None] >= pos[None, :])[None]
            kpm_b = (kpm.astype(f32)[:, None, :]
                     if kpm is not None else None)

            def per_head(args):
                # One head at a time: peak temporaries are [B,T,T], not
                # [B,H,T,T] — the dense reconstruction must not multiply
                # backward memory H-fold in the long-sequence regime this
                # kernel exists for.
                q_h, k_h, v_h, do_h, lse_h, delta_h, bias_h, valid_h = args
                s = jnp.einsum("bqd,bkd->bqk", q_h.astype(f32),
                               k_h.astype(f32), precision=precision,
                               preferred_element_type=f32) * scale
                if kpm_b is not None:
                    s = s * kpm_b if kpm_mode == 'mul' else s + kpm_b
                s_pre_bias = s
                bias_f = bias_h.astype(f32)
                s = s * bias_f if bias_mode == 'mul' else s + bias_f
                s = jnp.where(valid_h[None], s, NEG_INF)
                p = jnp.exp(s - lse_h.astype(f32))
                dp = jnp.einsum("bqd,bkd->bqk", do_h.astype(f32),
                                v_h.astype(f32), precision=precision,
                                preferred_element_type=f32)
                dS = p * (dp - delta_h.astype(f32))
                out = dS if bias_mode != 'mul' else dS * s_pre_bias
                return jnp.where(valid_h[None], out, 0.0).astype(bias.dtype)

            swap = lambda x: jnp.swapaxes(x, 0, 1)  # [B,H,...] -> [H,B,...]
            dbias = jnp.swapaxes(jax.lax.map(per_head, (
                swap(q), swap(k), swap(v), swap(do), swap(lse), swap(delta),
                swap(bias), jnp.asarray(valid_np))), 0, 1)
        return dq, dk, dv, dkpm, dbias

    attend.defvjp(attend_fwd, attend_bwd)
    return attend


def block_sparse_attention(q, k, v, layout, block, scale=None, causal=False,
                           key_padding_mask=None, key_padding_mask_mode='add',
                           attn_bias=None, attn_bias_mode='add'):
    """Block-sparse multi-head attention steered by a SparsityConfig layout.

    Args:
      q, k, v: [B, H, T, D]; T must be a multiple of `block`
        (SparseAttentionUtils.pad_to_block_size pads).
      layout: [H, T//block, T//block] 0/1 numpy array from
        SparsityConfig.make_layout.
      block: layout block size.
      causal: additionally apply an elementwise causal mask (the layouts from
        unidirectional configs are causal only at block granularity; this
        sharpens the diagonal blocks).
      key_padding_mask: [B, T] mask combined per mask mode ('add': added to
        scores; 'mul': multiplies scores — the reference softmax's semantics,
        softmax.py:17-40).
      attn_bias: [B, H, T, T] additive/multiplicative score bias — carries the
        reference's `rpe` and 2D `attn_mask` arguments.
    Returns: [B, H, T, D] in q.dtype.
    """
    b, h, t, d = q.shape
    if t % block != 0:
        raise ValueError('Sequence Length, {}, needs to be dividable by '
                         'Block size {}!'.format(t, block))
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    layout = np.asarray(layout)
    if layout.shape[0] != h:
        raise ValueError('layout heads {} != tensor heads {}'.format(
            layout.shape[0], h))
    # fp32 models contract at HIGHEST: the kernels accumulate in fp32, but
    # at DEFAULT the MXU rounds the fp32 OPERANDS to bf16 — fine when the
    # inputs started as bf16/fp16, silently lossy for fp32 parity.
    precision = _mxu_precision(q.dtype)
    key = (layout.tobytes(), layout.shape, int(block), float(scale),
           bool(causal), key_padding_mask is not None,
           attn_bias is not None, key_padding_mask_mode, attn_bias_mode,
           precision)
    fn = _FN_CACHE.get(key)
    if fn is None:
        fwd_lut, bwd_lut = build_luts(layout)
        fn = _make_fn(fwd_lut, bwd_lut, int(block), float(scale),
                      bool(causal), key_padding_mask is not None,
                      attn_bias is not None, key_padding_mask_mode,
                      attn_bias_mode, precision=precision)
        _FN_CACHE[key] = fn
    return fn(q, k, v, key_padding_mask, attn_bias)


def block_sparse_attention_reference(q, k, v, layout, block, scale=None,
                                     causal=False, key_padding_mask=None,
                                     key_padding_mask_mode='add',
                                     attn_bias=None, attn_bias_mode='add',
                                     precision=None):
    """Dense jnp ground truth: expand the block layout to an elementwise mask
    and run ordinary softmax attention. Used by parity tests.

    precision: forwarded to the einsums. When None, fp32 inputs default to
    'highest' — on TPU, DEFAULT rounds the fp32 operands to bf16 on the
    MXU, which would make the ground truth LESS accurate than the kernel
    under test (the kernel applies the same fp32->HIGHEST rule)."""
    if precision is None:
        precision = _mxu_precision(q.dtype)
    b, h, t, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    layout = np.asarray(layout)
    dense = np.kron(layout, np.ones((block, block)))[:, :t, :t]  # [H, T, T]
    s = jnp.einsum('bhqd,bhkd->bhqk', q.astype(jnp.float32),
                   k.astype(jnp.float32), precision=precision) * scale
    if key_padding_mask is not None:
        kpm = key_padding_mask.astype(jnp.float32)[:, None, None, :]
        s = s * kpm if key_padding_mask_mode == 'mul' else s + kpm
    if attn_bias is not None:
        ab = attn_bias.astype(jnp.float32)
        s = s * ab if attn_bias_mode == 'mul' else s + ab
    if causal:
        cm = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(cm[None, None], s, NEG_INF)
    s = jnp.where(jnp.asarray(dense, dtype=bool)[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # Fully-masked rows (no active blocks) produce zeros, matching the
    # kernel. Causality is already folded into `s` above.
    row_any = jnp.asarray(dense.any(-1), dtype=bool)[None, :, :, None]
    p = jnp.where(row_any, p, 0.0)
    return jnp.einsum('bhqk,bhkd->bhqd', p, v.astype(jnp.float32),
                      precision=precision).astype(q.dtype)
