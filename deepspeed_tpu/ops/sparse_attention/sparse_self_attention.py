"""SparseSelfAttention — layout-driven sparse attention orchestrator.

API mirror of the reference module (deepspeed/ops/sparse_attention/
sparse_self_attention.py:14-160): takes [B, H, T, D] q/k/v plus optional rpe /
key_padding_mask / attn_mask with 'add'/'mul' combine modes, steered by a
SparsityConfig.

TPU-native differences:
- the reference builds three Triton ops (sdd matmul, sparse softmax, dsd
  matmul) per sequence length and broadcasts the layout across ranks; here the
  layout is host-side trace metadata compiled into ONE fused Pallas kernel
  (kernels.block_sparse_attention), and there is nothing to synchronize —
  every process traces the same deterministic layout.
- masked (inactive) attention rows produce zeros instead of NaNs.
"""

import flax.linen as nn
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.kernels import block_sparse_attention
from deepspeed_tpu.ops.sparse_attention.sparsity_config import SparsityConfig


def sparse_self_attention(query, key, value, sparsity_config, rpe=None,
                          key_padding_mask=None, attn_mask=None,
                          key_padding_mask_mode='add', attn_mask_mode='mul',
                          causal=None):
    """Functional sparse self attention.

    Arguments follow the reference forward (sparse_self_attention.py:105-160):
      query/key/value: [B, H, T, D] (self-attention: identical shapes).
      rpe: optional relative-position score bias, [T, T], [H, T, T] or
        [B, H, T, T].
      key_padding_mask: optional [B, T], combined per key_padding_mask_mode.
      attn_mask: optional [T, T], combined per attn_mask_mode.
      causal: elementwise causal masking; default on iff the sparsity config
        is unidirectional.
    """
    if query.shape != key.shape or key.shape != value.shape:
        raise NotImplementedError('only self-attention is supported for now')
    b, h, t, d = query.shape
    layout = _layout_for(sparsity_config, t)
    if causal is None:
        causal = getattr(sparsity_config, 'attention', None) == 'unidirectional'

    bias = None
    bias_mode = 'add'
    if rpe is not None:
        bias = _broadcast_bias(jnp.asarray(rpe), b, h, t)
    if attn_mask is not None:
        am = jnp.asarray(attn_mask)
        if am.ndim != 2:
            raise NotImplementedError('currently only 2D attn_mask is supported')
        am = _broadcast_bias(am, b, h, t)
        if bias is None:
            bias = am
            bias_mode = attn_mask_mode
        else:
            # rpe is additive; fold a mul-mask in by shifting masked scores
            # far negative instead (same post-softmax result: zero weight).
            if attn_mask_mode == 'mul':
                bias = jnp.where(am != 0, bias, -1e30)
            else:
                bias = bias + am

    return block_sparse_attention(
        query, key, value, layout, sparsity_config.block,
        scale=float(d) ** -0.5, causal=causal,
        key_padding_mask=key_padding_mask,
        key_padding_mask_mode=key_padding_mask_mode,
        attn_bias=bias, attn_bias_mode=bias_mode)


# Keyed by a weak reference to the config object so a garbage-collected
# config can never alias a new one's cache slot (id() reuse), and entries die
# with their config.
import weakref

_LAYOUT_CACHE = weakref.WeakKeyDictionary()


def _layout_for(config, seq_len):
    per_config = _LAYOUT_CACHE.setdefault(config, {})
    if seq_len not in per_config:
        per_config[seq_len] = config.make_layout(seq_len)
    return per_config[seq_len]


def _broadcast_bias(x, b, h, t):
    if x.ndim == 2:
        x = x[None, None]
    elif x.ndim == 3:
        x = x[None]
    return jnp.broadcast_to(x, (b, h, t, t))


class SparseSelfAttention(nn.Module):
    """Module wrapper matching the reference class surface
    (sparse_self_attention.py:14-47)."""

    sparsity_config: SparsityConfig = None
    key_padding_mask_mode: str = 'add'
    attn_mask_mode: str = 'mul'
    max_seq_length: int = 2048  # accepted for API parity; layouts are built
                                # lazily per actual sequence length.

    def _config(self):
        return (self.sparsity_config if self.sparsity_config is not None
                else SparsityConfig(num_heads=4))

    @nn.compact
    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        return sparse_self_attention(
            query, key, value, self._config(), rpe=rpe,
            key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            key_padding_mask_mode=self.key_padding_mask_mode,
            attn_mask_mode=self.attn_mask_mode)
