"""Helpers for running sparse attention inside existing models — padding
sequences to block multiples and swapping attention layers
(reference deepspeed/ops/sparse_attention/sparse_attention_utils.py:14-225).
"""

import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.bert_sparse_self_attention import (
    BertSparseSelfAttention)


class SparseAttentionUtils:
    """Static helpers mirroring the reference class surface."""

    @staticmethod
    def extend_position_embedding(params, max_position):
        """Tile a learned position-embedding table out to `max_position` rows
        (reference :19-66 does this in-place on HF modules; here it maps over
        a param tree and returns the updated copy).

        `params` may be the embedding array itself or a dict containing an
        'embedding' entry (flax nn.Embed param layout).
        """
        def extend(table):
            orig = table.shape[0]
            if max_position <= orig:
                return table[:max_position]
            reps = -(-max_position // orig)
            return jnp.tile(table, (reps, 1))[:max_position]

        if isinstance(params, dict):
            out = dict(params)
            out['embedding'] = extend(jnp.asarray(params['embedding']))
            return out
        return extend(jnp.asarray(params))

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position):
        """Sync a HF tokenizer's model_max_length with the extended position
        embedding (reference :68-83)."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, 'init_kwargs'):
            tokenizer.init_kwargs['model_max_length'] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            model, max_position, sparsity_config=None):
        """Reference :85-121 mutates HF torch modules in place; the flax
        equivalent is module_inject-style tree surgery. See
        deepspeed_tpu.module_inject.replace_attn_with_sparse for the
        implementation; this wrapper exists for API parity."""
        from deepspeed_tpu.module_inject import replace_attn_with_sparse
        return replace_attn_with_sparse(model, max_position, sparsity_config)

    @staticmethod
    def replace_self_attention_layer_with_sparse_self_attention_layer(
            config, layers, sparsity_config=None):
        """Build BertSparseSelfAttention replacements for each given layer
        (reference :123-149)."""
        return [BertSparseSelfAttention(config=config,
                                        sparsity_config=sparsity_config)
                for _ in layers]

    @staticmethod
    def pad_to_block_size(block_size, input_ids, attention_mask,
                          token_type_ids, position_ids, inputs_embeds,
                          pad_token_id, model_embeddings):
        """Pad token/mask/embedding inputs along sequence length to a multiple
        of `block_size` (reference :151-208). Returns
        (pad_len, input_ids, attention_mask, token_type_ids, position_ids,
        inputs_embeds), each padded or passed through as None.
        """
        if input_ids is not None:
            seq_len = input_ids.shape[1]
        else:
            seq_len = inputs_embeds.shape[-2]
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len > 0:
            pad2 = ((0, 0), (0, pad_len))
            if inputs_embeds is not None:
                pad_ids = jnp.full((inputs_embeds.shape[0], pad_len),
                                   pad_token_id, dtype=jnp.int32)
                pad_embeds = model_embeddings(pad_ids)
                inputs_embeds = jnp.concatenate([inputs_embeds, pad_embeds],
                                                axis=-2)
            if input_ids is not None:
                input_ids = jnp.pad(input_ids, pad2,
                                    constant_values=pad_token_id)
            if position_ids is not None:
                position_ids = jnp.pad(position_ids, pad2,
                                       constant_values=pad_token_id)
            if attention_mask is not None:
                attention_mask = jnp.pad(attention_mask, pad2,
                                         constant_values=0)
            if token_type_ids is not None:
                token_type_ids = jnp.pad(token_type_ids, pad2,
                                         constant_values=0)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """Strip the padding added by pad_to_block_size (reference :210-224)."""
        if pad_len > 0:
            sequence_output = sequence_output[:, :-pad_len]
        return sequence_output
