"""Fused transformer layer + Pallas kernels (reference deepspeed/ops/transformer)."""

from deepspeed_tpu.ops.transformer.transformer import (  # noqa: F401
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer,
    transformer_layer_reference)
