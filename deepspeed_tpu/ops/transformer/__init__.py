"""Fused transformer layer + Pallas kernels (reference deepspeed/ops/transformer)."""

from deepspeed_tpu.ops.transformer.kernels.attention import (  # noqa: F401
    flash_attention, flash_attention_with_lse)
from deepspeed_tpu.ops.transformer.ring_attention import (  # noqa: F401
    ring_flash_attention, sequence_parallel_attention, ulysses_attention)
from deepspeed_tpu.ops.transformer.transformer import (  # noqa: F401
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer,
    transformer_layer_reference)
