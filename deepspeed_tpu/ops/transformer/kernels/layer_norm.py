"""Fused (bias + residual +) LayerNorm — TPU-native equivalent of the
reference's LN kernels (csrc/transformer/normalize_kernels.cu:
fused_bias_residual_layer_norm fwd at :16/:226, LayerNormBackward1/2 at
:607-1715 including the _fused_add residual variants).

Forward is one Pallas kernel: a single HBM read of x (+bias/+residual),
mean/var in fp32 on the VPU, one HBM write — the bandwidth profile the CUDA
kernels were written for. Backward uses the saved (mu, rstd): dx is a small
closed-form elementwise+row-reduction expression that XLA fuses into two
passes; dgamma/dbeta are column reductions (the reference's
LayerNormBackward1) which XLA maps to efficient tree reductions, so a
hand-written Pallas backward buys nothing on TPU.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret():
    return jax.default_backend() != "tpu"


def _pick_block_rows(n_rows, hidden):
    # Budget ~2 MB of VMEM for the x block in fp32.
    rows = max(8, min(n_rows, (2 * 1024 * 1024) // max(1, hidden * 4)))
    while n_rows % rows:
        rows //= 2
    return max(rows, 1)


def _ln_fwd_kernel(x_ref, g_ref, b_ref, o_ref, mu_ref, rstd_ref, *, eps,
                   bias_ref=None, res_ref=None):
    x = x_ref[...].astype(jnp.float32)
    if bias_ref is not None:
        x = x + bias_ref[...].astype(jnp.float32)
    if res_ref is not None:
        x = x + res_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)
    mu_ref[...] = mu
    rstd_ref[...] = rstd


def _ln_fwd(x, gamma, beta, bias, residual, eps):
    orig_shape = x.shape
    hidden = orig_shape[-1]
    x2 = x.reshape(-1, hidden)
    n = x2.shape[0]
    rows = _pick_block_rows(n, hidden)
    grid = (n // rows,)

    row_spec = pl.BlockSpec((rows, hidden), lambda i: (i, 0))
    gb_spec = pl.BlockSpec((hidden,), lambda i: (0,))
    stat_spec = pl.BlockSpec((rows, 1), lambda i: (i, 0))

    args = [x2, gamma, beta]
    in_specs = [row_spec, gb_spec, gb_spec]
    kwargs = {"eps": eps}
    kernel = _ln_fwd_kernel
    if bias is not None and residual is not None:
        def kernel(x_ref, g_ref, b_ref, bias_r, res_r, o_ref, mu_ref, rstd_ref):
            _ln_fwd_kernel(x_ref, g_ref, b_ref, o_ref, mu_ref, rstd_ref,
                           eps=eps, bias_ref=bias_r, res_ref=res_r)
        args += [bias, residual.reshape(-1, hidden)]
        in_specs += [gb_spec, row_spec]
    elif bias is not None or residual is not None:
        extra = bias if bias is not None else residual.reshape(-1, hidden)
        is_bias = bias is not None

        def kernel(x_ref, g_ref, b_ref, e_ref, o_ref, mu_ref, rstd_ref):
            _ln_fwd_kernel(x_ref, g_ref, b_ref, o_ref, mu_ref, rstd_ref,
                           eps=eps,
                           bias_ref=e_ref if is_bias else None,
                           res_ref=None if is_bias else e_ref)
        args.append(extra)
        in_specs.append(gb_spec if is_bias else row_spec)
    else:
        kernel = functools.partial(_ln_fwd_kernel, eps=eps)

    o, mu, rstd = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((n, hidden), x.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o.reshape(orig_shape), mu, rstd


def _ln_input(x, bias, residual):
    z = x.astype(jnp.float32)
    if bias is not None:
        z = z + bias.astype(jnp.float32)
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    return z


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused_ln(x, gamma, beta, bias, residual, eps):
    o, _, _ = _ln_fwd(x, gamma, beta, bias, residual, eps)
    return o


def _fused_ln_vjp_fwd(x, gamma, beta, bias, residual, eps):
    o, mu, rstd = _ln_fwd(x, gamma, beta, bias, residual, eps)
    return o, (x, gamma, bias, residual, mu, rstd)


def _fused_ln_vjp_bwd(eps, res, g):
    x, gamma, bias, residual, mu, rstd = res
    hidden = x.shape[-1]
    g2 = g.reshape(-1, hidden).astype(jnp.float32)
    z = _ln_input(x, bias, residual).reshape(-1, hidden)
    xhat = (z - mu) * rstd
    gg = g2 * gamma.astype(jnp.float32)
    # dx = rstd * (gg - mean(gg) - xhat * mean(gg * xhat))
    m1 = jnp.mean(gg, axis=-1, keepdims=True)
    m2 = jnp.mean(gg * xhat, axis=-1, keepdims=True)
    dz = (rstd * (gg - m1 - xhat * m2))
    dgamma = jnp.sum(g2 * xhat, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(g2, axis=0).astype(gamma.dtype)
    dx = dz.reshape(x.shape).astype(x.dtype)
    dbias = None if bias is None else jnp.sum(dz, axis=0).astype(bias.dtype)
    dres = None if residual is None else dx.astype(residual.dtype)
    return dx, dgamma, dbeta, dbias, dres


_fused_ln.defvjp(_fused_ln_vjp_fwd, _fused_ln_vjp_bwd)


def fused_layer_norm(x, gamma, beta, eps=1e-12):
    """LayerNorm over the last axis (reference launch_bias_residual_layer_norm
    with null residual)."""
    return _fused_ln(x, gamma, beta, None, None, float(eps))


def fused_bias_residual_layer_norm(x, residual, gamma, beta, bias=None,
                                   eps=1e-12):
    """LN(x + bias + residual) in one kernel — the reference's
    `fused_bias_residual_layer_norm` (normalize_kernels.cu:226), the
    post-attention/post-FFN LN of the fused transformer layer."""
    return _fused_ln(x, gamma, beta, bias, residual, float(eps))


def layer_norm_reference(x, gamma, beta, eps=1e-12):
    z = x.astype(jnp.float32)
    mu = jnp.mean(z, axis=-1, keepdims=True)
    var = jnp.var(z, axis=-1, keepdims=True)
    y = (z - mu) * jax.lax.rsqrt(var + eps) * gamma.astype(jnp.float32) \
        + beta.astype(jnp.float32)
    return y.astype(x.dtype)
