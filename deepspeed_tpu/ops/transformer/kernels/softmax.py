"""Fused scale+mask+softmax over attention scores — TPU-native equivalent of
reference csrc/transformer/softmax_kernels.cu (attn_softmax :9/:139,
launch_attn_softmax :290, softmax_backward_kernel_v2 :498).

Standalone op for the un-fused attention path and for tests; the flash
attention kernel (attention.py) subsumes it in the fused fast path. Backward
uses the classic dS = P * (dP - rowsum(dP * P)) with the saved probabilities,
matching the reference's backward_v2 contraction.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def _softmax_kernel(s_ref, o_ref, *, scale, causal, mask_ref=None):
    s = s_ref[...].astype(jnp.float32) * scale            # [1, 1, bq, T]
    if mask_ref is not None:
        s = s + mask_ref[...].astype(jnp.float32)[:, None, None, :]
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        iq = pl.program_id(2)
        q_pos = iq * t_q + jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    o_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(o_ref.dtype)


def _softmax_fwd(scores, mask, scale, causal):
    b, h, t_q, t_k = scores.shape
    block_q = t_q
    # Keep the [bq, T] tile within ~2 MB fp32 VMEM.
    while block_q > 8 and block_q * t_k * 4 > 2 * 1024 * 1024:
        block_q //= 2
    while t_q % block_q:
        block_q //= 2
    block_q = max(block_q, 1)
    grid = (b, h, t_q // block_q)
    spec = pl.BlockSpec((1, 1, block_q, t_k), lambda b_, h_, i: (b_, h_, i, 0))
    args = [scores]
    in_specs = [spec]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, t_k), lambda b_, h_, i: (b_, 0)))
        args.append(mask.astype(jnp.float32))

        def kernel(s_ref, m_ref, o_ref):
            _softmax_kernel(s_ref, o_ref, scale=scale, causal=causal,
                            mask_ref=m_ref)
    else:
        kernel = functools.partial(_softmax_kernel, scale=scale, causal=causal)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(scores.shape, scores.dtype),
        interpret=_interpret(),
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def attn_softmax(scores, mask, scale=1.0, causal=False):
    """softmax(scores * scale + mask [+ causal]) over the last axis.

    scores: [B, H, T_q, T_k]; mask: additive [B, T_k] or None.
    """
    return _softmax_fwd(scores, mask, scale, causal)


def _attn_softmax_fwd(scores, mask, scale, causal):
    p = _softmax_fwd(scores, mask, scale, causal)
    return p, p


def _attn_softmax_bwd(scale, causal, p, g):
    pf = p.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    ds = pf * (gf - jnp.sum(gf * pf, axis=-1, keepdims=True)) * scale
    return ds.astype(p.dtype), None


attn_softmax.defvjp(_attn_softmax_fwd, _attn_softmax_bwd)


def attn_softmax_reference(scores, mask=None, scale=1.0, causal=False):
    s = scores.astype(jnp.float32) * scale
    if mask is not None:
        s = s + mask[:, None, None, :].astype(jnp.float32)
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((t_q, t_k), dtype=bool))
        s = jnp.where(cm[None, None], s, NEG_INF)
    return jax.nn.softmax(s, axis=-1).astype(scores.dtype)
