"""Dropout with counter-based RNG — TPU-native equivalent of reference
csrc/transformer/dropout_kernels.cu (dropout_kernel :5, launch_dropout :257).

The CUDA kernels store a byte mask per element so backward can replay it.
On TPU the RNG is counter-based (threefry / pltpu PRNG), so the mask is a
pure function of (seed, offset): backward regenerates it instead of storing
it — zero mask memory, same semantics. The fused bias(+residual) variants
mirror the reference's `dropout_kernel` overloads that add bias/residual in
the same pass.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret():
    return jax.default_backend() != "tpu"


def _mask_from_bits(bits, rate):
    # bits: uint32. Keep when uniform(0,1) >= rate  <=>  bits >= rate * 2^32.
    threshold = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return (bits >= threshold).astype(jnp.float32)


def _dropout_kernel(x_ref, seed_ref, o_ref, *, rate, n_cols, bias_ref=None,
                    res_ref=None):
    i = pl.program_id(0)
    # Per-block seed: fold the block index into the scalar seed so every
    # block draws an independent, reproducible stream.
    pltpu.prng_seed(seed_ref[0] + i)
    x = x_ref[...].astype(jnp.float32)
    if bias_ref is not None:
        x = x + bias_ref[...].astype(jnp.float32)
    bits = pltpu.prng_random_bits(x.shape)
    keep = _mask_from_bits(pltpu.bitcast(bits, jnp.uint32), rate)
    y = x * keep * (1.0 / (1.0 - rate))
    if res_ref is not None:
        y = y + res_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _dropout_mask_jnp(shape, seed, rate):
    key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
    return (jax.random.uniform(key, shape) >= rate).astype(jnp.float32)


def _dropout_fwd(x, seed, rate, bias, residual):
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    n = x2.shape[0]
    if _interpret():
        # Off-TPU: identical semantics via threefry (pltpu PRNG only lowers
        # on real TPUs; interpret mode has no prng_seed primitive).
        z = x2.astype(jnp.float32)
        if bias is not None:
            z = z + bias.astype(jnp.float32)
        keep = _dropout_mask_jnp((n, hidden), seed, rate)
        y = z * keep * (1.0 / (1.0 - rate))
        if residual is not None:
            y = y + residual.reshape(-1, hidden).astype(jnp.float32)
        return y.astype(x.dtype).reshape(x.shape)

    rows = max(8, min(n, (2 * 1024 * 1024) // max(1, hidden * 4)))
    while n % rows:
        rows //= 2
    rows = max(rows, 1)
    row_spec = pl.BlockSpec((rows, hidden), lambda i: (i, 0))
    args = [x2, jnp.asarray([seed], jnp.int32)]
    in_specs = [row_spec, pl.BlockSpec(memory_space=pltpu.SMEM)]
    if bias is not None and residual is not None:
        def kernel(x_ref, s_ref, b_ref, r_ref, o_ref):
            _dropout_kernel(x_ref, s_ref, o_ref, rate=rate, n_cols=hidden,
                            bias_ref=b_ref, res_ref=r_ref)
        args += [bias, residual.reshape(-1, hidden)]
        in_specs += [pl.BlockSpec((hidden,), lambda i: (0,)), row_spec]
    elif bias is not None:
        def kernel(x_ref, s_ref, b_ref, o_ref):
            _dropout_kernel(x_ref, s_ref, o_ref, rate=rate, n_cols=hidden,
                            bias_ref=b_ref)
        args.append(bias)
        in_specs.append(pl.BlockSpec((hidden,), lambda i: (0,)))
    elif residual is not None:
        def kernel(x_ref, s_ref, r_ref, o_ref):
            _dropout_kernel(x_ref, s_ref, o_ref, rate=rate, n_cols=hidden,
                            res_ref=r_ref)
        args.append(residual.reshape(-1, hidden))
        in_specs.append(row_spec)
    else:
        kernel = functools.partial(_dropout_kernel, rate=rate, n_cols=hidden)

    o = pl.pallas_call(
        kernel,
        grid=(n // rows,),
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((n, hidden), x.dtype),
        interpret=False,
    )(*args)
    return o.reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _dropout(x, seed, rate, bias, residual):
    # seed is a (traced or concrete) int32 scalar — per-step seeds from the
    # flax dropout RNG flow through without retracing.
    return _dropout_fwd(x, seed, rate, bias, residual)


def _dropout_vjp_fwd(x, seed, rate, bias, residual):
    return _dropout_fwd(x, seed, rate, bias, residual), (x, seed, bias,
                                                         residual)


def _dropout_vjp_bwd(rate, res, g):
    x, seed, bias, residual = res
    hidden = x.shape[-1]
    n = x.size // hidden
    # Regenerate the identical mask from (seed, offset); matches what the
    # fwd kernel drew because both use the same counter stream.
    if _interpret():
        keep = _dropout_mask_jnp((n, hidden), seed, rate)
    else:
        keep = _regen_mask_tpu((n, hidden), seed, rate)
    dz = (g.reshape(-1, hidden).astype(jnp.float32) * keep
          * (1.0 / (1.0 - rate)))
    dx = dz.reshape(x.shape).astype(x.dtype)
    dbias = None if bias is None else jnp.sum(dz, axis=0).astype(bias.dtype)
    dres = None if residual is None else g.astype(residual.dtype)
    import numpy as _np
    dseed = _np.zeros((), dtype=jax.dtypes.float0)  # int arg: float0 cotangent
    return dx, dseed, dbias, dres


_dropout.defvjp(_dropout_vjp_fwd, _dropout_vjp_bwd)


def _mask_kernel(seed_ref, o_ref, *, rate):
    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0] + i)
    bits = pltpu.prng_random_bits(o_ref.shape)
    o_ref[...] = _mask_from_bits(pltpu.bitcast(bits, jnp.uint32), rate)


def _regen_mask_tpu(shape, seed, rate):
    n, hidden = shape
    rows = max(8, min(n, (2 * 1024 * 1024) // max(1, hidden * 4)))
    while n % rows:
        rows //= 2
    rows = max(rows, 1)
    return pl.pallas_call(
        functools.partial(_mask_kernel, rate=rate),
        grid=(n // rows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((rows, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, hidden), jnp.float32),
        interpret=False,
    )(jnp.asarray([seed], jnp.int32))


def dropout(x, rate, seed, deterministic=False):
    """Inverted dropout; mask reproducible from (seed)."""
    if deterministic or rate <= 0.0:
        return x
    return _dropout(x, jnp.asarray(seed, jnp.int32), float(rate), None, None)


def fused_bias_dropout_residual(x, bias, residual, rate, seed,
                                deterministic=False):
    """dropout(x + bias) + residual in one pass (reference
    dropout_kernels.cu bias/residual overloads) — the transformer layer's
    post-GEMM epilogue."""
    if deterministic or rate <= 0.0:
        y = x.astype(jnp.float32)
        if bias is not None:
            y = y + bias.astype(jnp.float32)
        if residual is not None:
            y = y + residual.astype(jnp.float32)
        return y.astype(x.dtype)
    return _dropout(x, jnp.asarray(seed, jnp.int32), float(rate), bias, residual)
