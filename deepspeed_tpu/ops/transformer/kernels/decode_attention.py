"""Flash-decode — length-aware fused cache attention for the slotted KV pool.

Serving reads attention differently than training writes it: the query is
one token (or one short prompt bucket) per row, the keys are a pre-allocated
``[B, H, max_len, D]`` cache plane, and each row has its own sequence
FRONTIER ``pos`` — row b's keys occupy ``0 .. pos[b]+S-1`` and everything
past that is stale garbage a future request will overwrite. The einsum path
in ``models/generation.py`` scores the query against the FULL plane in
fp32, materializes ``[B, H, S, max_len]`` scores and softmaxes over the
whole length, even when the frontier sits at position 30 of a 2048-slot
cache.

This kernel fuses QK-score, online softmax and the value GEMM in one
Pallas program, blocked along the length dimension, with PER-ROW frontier
awareness via scalar prefetch:

- ``pos`` rides a ``PrefetchScalarGridSpec`` scalar operand, so the kv
  BLOCK INDEX MAP can read it: blocks past ``(pos[b]+S-1) // block_k``
  clamp to the last useful block (a repeated index issues no new DMA) and
  ``@pl.when`` skips their compute — the same trick the training kernel
  uses for causal skip, but against a runtime frontier instead of the
  static diagonal;
- scores never leave VMEM: online-softmax statistics live in fp32 scratch
  across the split-KV grid steps, and the row-sum rides the PV matmul
  (``_pv_rowsum``) exactly as in the training kernel;
- the frontier mask only costs a compare/select pass on the one block that
  STRADDLES a row's frontier; fully-visible interior blocks skip it;
- q is pre-scaled by 1/sqrt(d) outside the kernel, and decode's S=1 query
  is padded up to the Mosaic sublane minimum (8 fp32 / 16 bf16) so the
  [S, block_k] score tile is always a legal VMEM shape.

The cache plane length must be a multiple of ``BLOCK_MIN`` (128 lanes);
``inference/kv_pool.py`` pads its pool to that quantum and
``flash_decode_attention`` falls back to the dense reference for
unsupported shapes. Off-TPU the kernel runs in Pallas interpret mode, so
CPU tests exercise the same code path (parity pinned by
``tests/unit/test_decode_attention.py``).
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.analysis.annotations import hot_path
from deepspeed_tpu.ops.transformer.kernels.attention import (
    NEG_INF,
    _STATS_LANES,
    _bh_spec,
    _def_partition,
    _exp_lowp,
    _interpret,
    _is_lowp,
    _mxu_precision,
    _pv_rowsum,
    _use_custom_partitioning,
)

# Length-dimension tile quantum: one 128-lane row of the score tile. The
# kv pool pads max_len to a multiple of this so the kernel always engages.
BLOCK_MIN = 128

_DEFAULT_BLOCK_K = 256


def pad_cache_len(max_len):
    """Smallest multiple of BLOCK_MIN covering ``max_len`` — the cache
    plane length flash-decode requires (padding a plane is inert: the
    frontier never reaches padded positions, so they are always masked)."""
    return -(-int(max_len) // BLOCK_MIN) * BLOCK_MIN


def decode_supported(t_kv):
    """Can the kernel take a cache plane of length ``t_kv``?"""
    return t_kv % BLOCK_MIN == 0


def _sublane(dtype):
    """Mosaic's minimum second-minor tile extent: score tiles narrower than
    this are padded anyway, so the launcher pads the QUERY dim explicitly
    and slices the output (decode's S=1 would otherwise hand Mosaic a
    1-row tile)."""
    return 16 if _is_lowp(dtype) else 8


def decode_signature(b, h, s, t_kv, d, dtype):
    """Autotune-table signature for a decode-attention shape. Exported so
    the sweep/promotion script (tests/perf/autotune_sweep.py) shares the
    exact format and cannot silently drop entries if it changes."""
    return "b{}_h{}_s{}_t{}_d{}_{}".format(
        b, h, s, t_kv, d, jnp.dtype(dtype).name)


# ---------------------------------------------------------------------------
# int8 KV quantization — the storage format of the KV hierarchy's
# compressed tier (inference/kv_hierarchy/). Symmetric per-(head, position)
# scales: each written position gets its own scale, so APPENDING never
# retroactively re-quantizes earlier positions (a running per-head amax
# would corrupt history on every new outlier). The scale planes ride the
# pool as fp32 ``[..., T]`` arrays — 2 bytes/position of overhead against
# the (itemsize-1)*D saved per position.
# ---------------------------------------------------------------------------

# Scale floor: all-zero rows (unwritten cache positions) quantize to zero
# codes with this scale instead of dividing by zero.
_Q8_EPS = 1e-8


@hot_path
def quantize_kv(x):
    """Quantize ``[..., D]`` k/v rows to int8 with per-row symmetric
    scales. Returns ``(codes int8 [..., D], scale fp32 [...])`` where
    ``codes * scale[..., None]`` reconstructs x to within scale/2 per
    element (the parity bound tests pin)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / 127.0, _Q8_EPS)
    codes = jnp.clip(jnp.round(xf / scale[..., None]), -127.0, 127.0)
    return codes.astype(jnp.int8), scale


@hot_path
def dequantize_kv(codes, scale, dtype=jnp.float32):
    """Inverse of ``quantize_kv``: ``codes [..., D]`` int8 with per-row
    ``scale [...]`` back to ``dtype``."""
    return (codes.astype(jnp.float32) * scale[..., None]).astype(dtype)


# ---------------------------------------------------------------------------
# Reference (pure jnp) — ground truth for parity tests and the fallback for
# shapes the kernel does not support. Mirrors models/generation.py's cache
# attention (einsum scores over the full plane, frontier mask, fp32
# softmax) so flag-off and fallback paths are the SAME math.
# ---------------------------------------------------------------------------

@hot_path
def decode_attention_reference(q, k, v, pos, scale=None):
    """q: [B, H, S, D] query rows, row b starting at global position
    ``pos[b]`` (its k/v already written at ``pos[b] .. pos[b]+S-1``);
    k, v: [B, H, T, D] cache planes; pos: [B] int32 frontiers.
    Key t is visible to query row i iff ``t <= pos[b] + i`` — the causal
    mask against each row's GLOBAL position, which also excludes every
    stale position past the frontier. Returns [B, H, S, D] in q.dtype."""
    B, H, S, D = q.shape
    T = k.shape[2]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    prec = _mxu_precision(q.dtype)
    q_pos = pos[:, None] + jnp.arange(S)[None]               # [B, S]
    mask = jnp.arange(T)[None, None, :] <= q_pos[:, :, None]  # [B, S, T]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32), precision=prec) * scale
    s = jnp.where(mask[:, None], s, jnp.finfo(jnp.float32).min)
    att = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", att, v, precision=prec)


@hot_path
def decode_attention_q8_reference(q, k, v, k_scale, v_scale, pos,
                                  scale=None):
    """int8-cache ground truth: dequantize the whole plane, then the
    dense reference. The q8 kernel must match THIS — the engine's einsum
    (flag-off) path computes exactly this, so kernel-on and kernel-off
    serving agree on the same dequantized math.

    k, v: [B, H, T, D] int8 codes; k_scale, v_scale: [B, H, T] fp32
    per-position scales."""
    kf = dequantize_kv(k, k_scale, q.dtype)
    vf = dequantize_kv(v, v_scale, q.dtype)
    return decode_attention_reference(q, kf, vf, pos, scale=scale)


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------

def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, *scratch,
                   s_len, block_k, single_kv):
    b_ = pl.program_id(0)
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)
    pos_b = pos_ref[b_]
    # Last kv block holding any key visible to this row's queries: the
    # frontier analogue of the training kernel's _last_kv_block(iq).
    last = (pos_b + s_len - 1) // block_k

    def scores():
        s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_mxu_precision(q_ref.dtype))

        def straddling():
            # Key col (global j*block_k + c) visible to query row i
            # (global pos_b + i) iff k_pos <= q_pos. Padded query rows
            # (i >= s_len) compute garbage the launcher slices off.
            q_pos = pos_b + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            return jnp.where(k_pos <= q_pos, s, NEG_INF)

        # Interior blocks (every key visible to even the FIRST query row)
        # skip the iota/compare/select pass — only the block straddling the
        # frontier pays for masking.
        return jax.lax.cond((j + 1) * block_k - 1 <= pos_b,
                            lambda: s, straddling)

    if single_kv:
        # One kv block: direct softmax, no scratch, no rescale passes.
        s = scores()
        m = jnp.max(s, axis=-1, keepdims=True)
        p = _exp_lowp(s - m, o_ref.dtype)
        pv, l = _pv_rowsum(p, v_ref[0, 0])
        l = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (pv / l).astype(o_ref.dtype)
        return

    acc, m_s, l_s = scratch

    @pl.when(j == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    @pl.when(j <= last)
    def _compute():
        s = scores()
        m_prev = m_s[:, 0:1]
        l_prev = l_s[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = _exp_lowp(s - m_new, o_ref.dtype)
        pv, l_cur = _pv_rowsum(p, v_ref[0, 0])
        l_new = alpha * l_prev + l_cur
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)
        acc[...] = acc[...] * alpha + pv

    # The grid is dense (skipped blocks still step), so the last step
    # always runs and can finalize unconditionally.
    @pl.when(j == n_kv - 1)
    def _finalize():
        l = jnp.maximum(l_s[:, 0:1], 1e-30)
        o_ref[0, 0] = (acc[...] / l).astype(o_ref.dtype)


def _flash_decode_pallas(q, k, v, pos, scale, block_k):
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    t_kv = k.shape[2]
    n_kv = t_kv // block_k
    # Pre-scale q: one [S, d] pass replaces a [S, T] pass per kernel.
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    pos = pos.astype(jnp.int32)
    # Pad the query dim up to the sublane minimum (decode is S=1); padded
    # rows compute garbage that is sliced off below.
    sub = _sublane(q.dtype)
    s_blk = -(-s // sub) * sub
    if s_blk != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_blk - s), (0, 0)))

    def kv_index(b_, h_, j, pos_ref):
        # Clamp past-frontier blocks to the last useful one: a repeated
        # block index issues no new DMA, and @pl.when skips the compute.
        last = (pos_ref[b_] + s - 1) // block_k
        return (b_, h_, jnp.minimum(j, last), 0)

    def q_index(b_, h_, j, pos_ref):
        return (b_, h_, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, n_kv),
        in_specs=[
            pl.BlockSpec((1, 1, s_blk, d), q_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, s_blk, d), q_index),
        scratch_shapes=[] if n_kv == 1 else [
            pltpu.VMEM((s_blk, d), jnp.float32),
            pltpu.VMEM((s_blk, _STATS_LANES), jnp.float32),
            pltpu.VMEM((s_blk, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, s_len=s, block_k=block_k,
                          single_kv=n_kv == 1),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s_blk, d), q.dtype),
        interpret=_interpret(),
    )(pos, q, k, v)
    return out[:, :, :s] if s_blk != s else out


# ---------------------------------------------------------------------------
# int8 kernel (family "decode_attention_q8") — the same online-softmax
# program over int8 k/v planes, dequantizing IN-BLOCK: each kv block's
# codes meet their per-position scales in VMEM, so HBM traffic on the
# length dim drops ~4x (int8 codes + one fp32 scale lane vs fp32 rows)
# and the pool stores int8. Frontier clamping, straddle-only masking and
# the scratch accumulator are identical to the fp kernel.
# ---------------------------------------------------------------------------

def _decode_kernel_q8(pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                      *scratch, s_len, block_k, single_kv):
    b_ = pl.program_id(0)
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)
    pos_b = pos_ref[b_]
    last = (pos_b + s_len - 1) // block_k

    def dequant():
        # In-block dequant: int8 codes * fp32 per-position scales
        # ([block_k, 1] broadcast over [block_k, d]). k stays fp32 into
        # the score GEMM; v casts to the output dtype for _pv_rowsum,
        # matching the fp kernel's operand dtype there.
        k_f = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0]
        v_f = (v_ref[0, 0].astype(jnp.float32)
               * vs_ref[0, 0]).astype(o_ref.dtype)
        return k_f, v_f

    def scores(k_f):
        s = jax.lax.dot_general(q_ref[0, 0].astype(jnp.float32), k_f,
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_mxu_precision(jnp.float32))

        def straddling():
            q_pos = pos_b + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            return jnp.where(k_pos <= q_pos, s, NEG_INF)

        return jax.lax.cond((j + 1) * block_k - 1 <= pos_b,
                            lambda: s, straddling)

    if single_kv:
        k_f, v_f = dequant()
        s = scores(k_f)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = _exp_lowp(s - m, o_ref.dtype)
        pv, l = _pv_rowsum(p, v_f)
        l = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (pv / l).astype(o_ref.dtype)
        return

    acc, m_s, l_s = scratch

    @pl.when(j == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    @pl.when(j <= last)
    def _compute():
        k_f, v_f = dequant()
        s = scores(k_f)
        m_prev = m_s[:, 0:1]
        l_prev = l_s[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = _exp_lowp(s - m_new, o_ref.dtype)
        pv, l_cur = _pv_rowsum(p, v_f)
        l_new = alpha * l_prev + l_cur
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)
        acc[...] = acc[...] * alpha + pv

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = jnp.maximum(l_s[:, 0:1], 1e-30)
        o_ref[0, 0] = (acc[...] / l).astype(o_ref.dtype)


def _flash_decode_q8_pallas(q, k, v, k_scale, v_scale, pos, scale, block_k):
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    t_kv = k.shape[2]
    n_kv = t_kv // block_k
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    pos = pos.astype(jnp.int32)
    # Scales block along the length dim like k/v, so they need length
    # second-minor too: [B, H, T] -> [B, H, T, 1]. The 1-lane trailing
    # axis pads to a full lane tile in VMEM (the _STATS_LANES trade: a
    # few wasted lanes for a legal layout).
    k_scale = k_scale.astype(jnp.float32)[..., None]
    v_scale = v_scale.astype(jnp.float32)[..., None]
    sub = _sublane(q.dtype)
    s_blk = -(-s // sub) * sub
    if s_blk != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_blk - s), (0, 0)))

    def kv_index(b_, h_, j, pos_ref):
        last = (pos_ref[b_] + s - 1) // block_k
        return (b_, h_, jnp.minimum(j, last), 0)

    def q_index(b_, h_, j, pos_ref):
        return (b_, h_, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, n_kv),
        in_specs=[
            pl.BlockSpec((1, 1, s_blk, d), q_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, 1), kv_index),
            pl.BlockSpec((1, 1, block_k, 1), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, s_blk, d), q_index),
        scratch_shapes=[] if n_kv == 1 else [
            pltpu.VMEM((s_blk, d), jnp.float32),
            pltpu.VMEM((s_blk, _STATS_LANES), jnp.float32),
            pltpu.VMEM((s_blk, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel_q8, s_len=s, block_k=block_k,
                          single_kv=n_kv == 1),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s_blk, d), q.dtype),
        interpret=_interpret(),
    )(pos, q, k, v, k_scale, v_scale)
    return out[:, :, :s] if s_blk != s else out


# ---------------------------------------------------------------------------
# Block selection — autotuner integration (kernel families
# "decode_attention" and "decode_attention_q8"; see ops/autotuner.py and
# tests/perf/autotune_sweep.py)
# ---------------------------------------------------------------------------

def _block_candidates(t_kv):
    return [bk for bk in (128, 256, 512) if bk <= t_kv and t_kv % bk == 0]


def _autotuned_block(shape, dtype, cands, default, arrays=None,
                     family="decode_attention"):
    """Consult the autotuner for a decode block size. ``arrays`` (operand
    concrete values: q, k, v for the fp family; q, codes, codes, scales,
    scales for q8) enables an online sweep under DS_TPU_AUTOTUNE; without
    them (traced engine calls, bench stamping) only the bundled/user
    tables are consulted. The sweep times the WORST-CASE frontier
    (pos = t - s: every block active) so the tuned tile is the one the
    end of a long generation runs on."""
    from deepspeed_tpu.ops import autotuner

    b, h, s, t_kv, d = shape
    sig = decode_signature(b, h, s, t_kv, d, dtype)
    cand_lists = [[c] for c in cands] if arrays is not None else []

    def make_run(cand):
        (bk,) = cand
        pos = jnp.full((b,), t_kv - s, jnp.int32)
        scale = 1.0 / (d ** 0.5)
        if family == "decode_attention_q8":
            q, kq, vq, ks, vs = arrays[:5]
            jitted = jax.jit(functools.partial(
                _flash_decode_q8_pallas, scale=scale, block_k=int(bk)))

            def run():
                return jitted(q, kq, vq, ks, vs, pos)
        else:
            q, k, v = arrays[:3]
            jitted = jax.jit(functools.partial(
                _flash_decode_pallas, scale=scale, block_k=int(bk)))

            def run():
                return jitted(q, k, v, pos)
        return run

    choice = autotuner.autotune(family, sig, cand_lists,
                                make_run, default=[default])
    bk = int(choice[0] if isinstance(choice, (list, tuple)) else choice)
    # A hand-edited table entry must not break dispatch: reject tiles the
    # kernel cannot take and fall back to the default.
    return bk if bk >= 1 and t_kv % bk == 0 else default


def planned_block_k(b, h, s, t_kv, d, dtype):
    """Table-or-default block_k for a decode shape WITHOUT running a sweep
    (bench stamping / observability). None when the kernel cannot take the
    shape at all."""
    if not decode_supported(t_kv):
        return None
    cands = _block_candidates(t_kv)
    default = _DEFAULT_BLOCK_K if _DEFAULT_BLOCK_K in cands else cands[-1]
    return _autotuned_block((b, h, s, t_kv, d), dtype, cands, default)


def resolve_decode_block(q, k, block_k=None, v=None, pos=None, scales=None,
                         family="decode_attention"):
    """The ONE block-selection policy for flash_decode_attention (both
    families): an explicit ``block_k`` (arg or DS_TPU_FLASH_DECODE_BLOCK
    env, for tests and A/B experiments) is honored when legal; otherwise
    the autotuner table / default — with an online sweep when the call is
    eager on TPU and DS_TPU_AUTOTUNE is on (v/pos — plus ``scales`` for
    q8 — supply the sweep operands). Returns None when the shape must
    take the dense fallback."""
    import jax.core

    t_kv = k.shape[2]
    if block_k is None:
        env_bk = os.environ.get("DS_TPU_FLASH_DECODE_BLOCK", "")
        if env_bk:
            block_k = int(env_bk)
    if block_k is not None:
        bk = min(int(block_k), t_kv)
        return bk if bk >= 1 and t_kv % bk == 0 else None
    if not decode_supported(t_kv):
        return None
    b, h, s, d = q.shape
    cands = _block_candidates(t_kv)
    default = _DEFAULT_BLOCK_K if _DEFAULT_BLOCK_K in cands else cands[-1]
    operands = (q, k, v, pos) + (tuple(scales) if scales else ())
    traced = any(isinstance(x, jax.core.Tracer)
                 for x in operands if x is not None)
    arrays = None
    if not traced and not _interpret() and v is not None and pos is not None:
        arrays = (q, k, v) + (tuple(scales) if scales else ())
    return _autotuned_block((b, h, s, t_kv, d), q.dtype, cands, default,
                            arrays=arrays, family=family)


# ---------------------------------------------------------------------------
# GSPMD integration — batch/head-parallel partitioning, mirroring
# attention.py's _cp_wrap (b/h follow the operand sharding, length and
# head-dim replicate; pos is a [B] vector sharded like the batch dim).
# Without the rule XLA would replicate the whole kv pool into every shard.
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _decode_partitioned(scale, block_k):
    def f(q, k, v, pos):
        return _flash_decode_pallas(q, k, v, pos, scale, block_k)

    cp = custom_partitioning(f)

    def shardings(mesh, q_sharding):
        b, h = _bh_spec(q_sharding)
        full = NamedSharding(mesh, P(b, h, None, None))
        pos_sh = NamedSharding(mesh, P(b))
        return (full, full, full, pos_sh), (full,)

    def infer(mesh, arg_shapes, shape):
        return shardings(mesh, arg_shapes[0].sharding)[1][0]

    def partition(mesh, arg_shapes, result_shape):
        args, outs = shardings(mesh, arg_shapes[0].sharding)
        return mesh, f, outs[0], args

    # Factors ordered by first appearance in the rule (Shardy requires
    # sorted factor indices): t, d (from q), s (from k).
    _def_partition(cp, partition, infer,
                   "b h t d, b h s d, b h s d, b -> b h t d",
                   ("t", "d", "s"))
    return cp


@functools.lru_cache(maxsize=None)
def _decode_q8_partitioned(scale, block_k):
    def f(q, k, v, k_scale, v_scale, pos):
        return _flash_decode_q8_pallas(q, k, v, k_scale, v_scale, pos,
                                       scale, block_k)

    cp = custom_partitioning(f)

    def shardings(mesh, q_sharding):
        b, h = _bh_spec(q_sharding)
        full = NamedSharding(mesh, P(b, h, None, None))
        sc = NamedSharding(mesh, P(b, h, None))
        pos_sh = NamedSharding(mesh, P(b))
        return (full, full, full, sc, sc, pos_sh), (full,)

    def infer(mesh, arg_shapes, shape):
        return shardings(mesh, arg_shapes[0].sharding)[1][0]

    def partition(mesh, arg_shapes, result_shape):
        args, outs = shardings(mesh, arg_shapes[0].sharding)
        return mesh, f, outs[0], args

    # Scale planes shard exactly like their codes minus the head dim:
    # [b, h, s] follows the kv sharding, length replicated.
    _def_partition(cp, partition, infer,
                   "b h t d, b h s d, b h s d, b h s, b h s, b -> b h t d",
                   ("t", "d", "s"))
    return cp


# ---------------------------------------------------------------------------
# Public entry point
# ---------------------------------------------------------------------------

@hot_path
def flash_decode_attention(q, k, v, pos, scale=None, block_k=None):
    """Length-aware fused cache attention over a slotted KV plane.

    Args:
      q: [B, H, S, D] query rows; row b's tokens sit at global positions
        ``pos[b] .. pos[b]+S-1`` (S=1 in the decode scan, S=bucket in
        prefill). The row's k/v must ALREADY be written into the plane —
        the convention of models/generation.py's _forward, which writes
        the cache before attending.
      k, v: [B, H, T, D] cache planes; T must be a multiple of BLOCK_MIN
        (128) for the kernel to engage (inference/kv_pool.py pads its
        pool; unsupported T falls back to the dense reference).
      pos: [B] int32 per-row frontiers (pre-write sequence lengths).
      scale: score scale; default 1/sqrt(D).
      block_k: length-dim tile; default consults the autotuner
        ("decode_attention" family). DS_TPU_FLASH_DECODE_BLOCK overrides.
    Returns: [B, H, S, D] in q.dtype.
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    bk = resolve_decode_block(q, k, block_k=block_k, v=v, pos=pos)
    if bk is None:
        return decode_attention_reference(q, k, v, pos, scale=scale)
    if _use_custom_partitioning():
        return _decode_partitioned(float(scale), int(bk))(q, k, v, pos)
    return _flash_decode_pallas(q, k, v, pos, float(scale), int(bk))


@hot_path
def flash_decode_attention_q8(q, k, v, k_scale, v_scale, pos, scale=None,
                              block_k=None):
    """int8-cache flash decode: same contract as ``flash_decode_attention``
    but k/v are int8 codes with fp32 per-(head, position) scales
    (``quantize_kv``'s output layout, [B, H, T] alongside [B, H, T, D]
    planes). Dequantization happens in-block inside the kernel; shapes
    the kernel cannot take fall back to ``decode_attention_q8_reference``
    (dequantize-then-dense). Autotuned under the "decode_attention_q8"
    family — int8 operands shift the compute/bandwidth balance, so tiles
    are tuned separately from the fp family."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    bk = resolve_decode_block(q, k, block_k=block_k, v=v, pos=pos,
                              scales=(k_scale, v_scale),
                              family="decode_attention_q8")
    if bk is None:
        return decode_attention_q8_reference(q, k, v, k_scale, v_scale,
                                             pos, scale=scale)
    if _use_custom_partitioning():
        return _decode_q8_partitioned(float(scale), int(bk))(
            q, k, v, k_scale, v_scale, pos)
    return _flash_decode_q8_pallas(q, k, v, k_scale, v_scale, pos,
                                   float(scale), int(bk))


# ---------------------------------------------------------------------------
# Paged kernels (families "decode_attention_paged[_q8]") — block-table
# flash decode over the paged KV pool's page ARENA (inference/kv_pool.py
# paged layout). The arena is [P, H, page_len, D] per layer and each row's
# logical plane is named by an int32 block table [B, n_lp]: logical block
# j of row b lives in arena page ``tbl[b, j]``. KERNEL BLOCKS == PAGES:
# block_k is page_len, so the only new machinery is the kv index map —
# it rides a second scalar-prefetch operand (the table) and resolves
# (b, j) -> arena page, with the SAME past-frontier clamp (a repeated
# page index issues no new DMA) and the same straddle-only masking; the
# kernel bodies are the dense bodies unchanged (global key positions are
# j * page_len + lane, exactly as dense).
# ---------------------------------------------------------------------------

def _decode_kernel_paged(pos_ref, tbl_ref, *rest, **kw):
    # The table is consumed ENTIRELY by the index maps; the body math is
    # the dense kernel's.
    return _decode_kernel(pos_ref, *rest, **kw)


def _decode_kernel_paged_q8(pos_ref, tbl_ref, *rest, **kw):
    return _decode_kernel_q8(pos_ref, *rest, **kw)


@hot_path
def decode_attention_paged_reference(q, k, v, block_tbl, pos, scale=None):
    """Paged ground truth: gather each row's pages into its dense
    logical plane, then the dense reference — the same math the engine's
    einsum (flag-off) path computes, so kernel-on and kernel-off paged
    serving agree bit-for-bit.

    q: [B, H, S, D]; k, v: [P, H, page_len, D] page arenas;
    block_tbl: [B, n_lp] int32; pos: [B] int32 frontiers."""
    B, H = q.shape[0], q.shape[1]
    page_len = k.shape[2]
    T = block_tbl.shape[1] * page_len

    def gather(arena):
        g = jnp.take(arena, block_tbl, axis=0)     # [B, n_lp, H, p, ...]
        g = jnp.moveaxis(g, 2, 1)                  # [B, H, n_lp, p, ...]
        return g.reshape((B, H, T) + g.shape[4:])

    return decode_attention_reference(q, gather(k), gather(v), pos,
                                      scale=scale)


@hot_path
def decode_attention_paged_q8_reference(q, k, v, k_scale, v_scale,
                                        block_tbl, pos, scale=None):
    """int8 paged ground truth: gather codes AND scales through the
    table, dequantize, then the dense reference."""
    B, H = q.shape[0], q.shape[1]
    page_len = k.shape[2]
    T = block_tbl.shape[1] * page_len

    def gather(arena):
        g = jnp.take(arena, block_tbl, axis=0)
        g = jnp.moveaxis(g, 2, 1)
        return g.reshape((B, H, T) + g.shape[4:])

    kf = dequantize_kv(gather(k), gather(k_scale), q.dtype)
    vf = dequantize_kv(gather(v), gather(v_scale), q.dtype)
    return decode_attention_reference(q, kf, vf, pos, scale=scale)


def _flash_decode_paged_pallas(q, k, v, tbl, pos, scale):
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    page_len = k.shape[2]
    n_lp = tbl.shape[1]
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    pos = pos.astype(jnp.int32)
    tbl = tbl.astype(jnp.int32)
    sub = _sublane(q.dtype)
    s_blk = -(-s // sub) * sub
    if s_blk != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_blk - s), (0, 0)))

    def kv_index(b_, h_, j, pos_ref, tbl_ref):
        # Logical block j of row b_ lives in arena page tbl[b_, j];
        # past-frontier blocks clamp to the last useful LOGICAL block
        # first, so the resolved PAGE repeats and issues no new DMA.
        last = (pos_ref[b_] + s - 1) // page_len
        return (tbl_ref[b_, jnp.minimum(j, last)], h_, 0, 0)

    def q_index(b_, h_, j, pos_ref, tbl_ref):
        return (b_, h_, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, n_lp),
        in_specs=[
            pl.BlockSpec((1, 1, s_blk, d), q_index),
            pl.BlockSpec((1, 1, page_len, d), kv_index),
            pl.BlockSpec((1, 1, page_len, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, s_blk, d), q_index),
        scratch_shapes=[] if n_lp == 1 else [
            pltpu.VMEM((s_blk, d), jnp.float32),
            pltpu.VMEM((s_blk, _STATS_LANES), jnp.float32),
            pltpu.VMEM((s_blk, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel_paged, s_len=s, block_k=page_len,
                          single_kv=n_lp == 1),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s_blk, d), q.dtype),
        interpret=_interpret(),
    )(pos, tbl, q, k, v)
    return out[:, :, :s] if s_blk != s else out


def _flash_decode_paged_q8_pallas(q, k, v, k_scale, v_scale, tbl, pos,
                                  scale):
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    page_len = k.shape[2]
    n_lp = tbl.shape[1]
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    pos = pos.astype(jnp.int32)
    tbl = tbl.astype(jnp.int32)
    k_scale = k_scale.astype(jnp.float32)[..., None]
    v_scale = v_scale.astype(jnp.float32)[..., None]
    sub = _sublane(q.dtype)
    s_blk = -(-s // sub) * sub
    if s_blk != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_blk - s), (0, 0)))

    def kv_index(b_, h_, j, pos_ref, tbl_ref):
        last = (pos_ref[b_] + s - 1) // page_len
        return (tbl_ref[b_, jnp.minimum(j, last)], h_, 0, 0)

    def q_index(b_, h_, j, pos_ref, tbl_ref):
        return (b_, h_, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, n_lp),
        in_specs=[
            pl.BlockSpec((1, 1, s_blk, d), q_index),
            pl.BlockSpec((1, 1, page_len, d), kv_index),
            pl.BlockSpec((1, 1, page_len, d), kv_index),
            pl.BlockSpec((1, 1, page_len, 1), kv_index),
            pl.BlockSpec((1, 1, page_len, 1), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, s_blk, d), q_index),
        scratch_shapes=[] if n_lp == 1 else [
            pltpu.VMEM((s_blk, d), jnp.float32),
            pltpu.VMEM((s_blk, _STATS_LANES), jnp.float32),
            pltpu.VMEM((s_blk, _STATS_LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel_paged_q8, s_len=s,
                          block_k=page_len, single_kv=n_lp == 1),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s_blk, d), q.dtype),
        interpret=_interpret(),
    )(pos, tbl, q, k, v, k_scale, v_scale)
    return out[:, :, :s] if s_blk != s else out


@functools.lru_cache(maxsize=None)
def _decode_paged_partitioned(scale):
    def f(q, k, v, tbl, pos):
        return _flash_decode_paged_pallas(q, k, v, tbl, pos, scale)

    cp = custom_partitioning(f)

    def shardings(mesh, q_sharding):
        b, h = _bh_spec(q_sharding)
        full = NamedSharding(mesh, P(b, h, None, None))
        # The arena's page dim replicates (every shard must reach every
        # page — the table is data, not layout); heads shard like q.
        arena = NamedSharding(mesh, P(None, h, None, None))
        tbl_sh = NamedSharding(mesh, P(b, None))
        pos_sh = NamedSharding(mesh, P(b))
        return (full, arena, arena, tbl_sh, pos_sh), (full,)

    def infer(mesh, arg_shapes, shape):
        return shardings(mesh, arg_shapes[0].sharding)[1][0]

    def partition(mesh, arg_shapes, result_shape):
        args, outs = shardings(mesh, arg_shapes[0].sharding)
        return mesh, f, outs[0], args

    # Factors ordered by first appearance: t, d (q), p, s (arena),
    # n (table).
    _def_partition(cp, partition, infer,
                   "b h t d, p h s d, p h s d, b n, b -> b h t d",
                   ("t", "d", "p", "s", "n"))
    return cp


@functools.lru_cache(maxsize=None)
def _decode_paged_q8_partitioned(scale):
    def f(q, k, v, k_scale, v_scale, tbl, pos):
        return _flash_decode_paged_q8_pallas(q, k, v, k_scale, v_scale,
                                             tbl, pos, scale)

    cp = custom_partitioning(f)

    def shardings(mesh, q_sharding):
        b, h = _bh_spec(q_sharding)
        full = NamedSharding(mesh, P(b, h, None, None))
        arena = NamedSharding(mesh, P(None, h, None, None))
        sc = NamedSharding(mesh, P(None, h, None))
        tbl_sh = NamedSharding(mesh, P(b, None))
        pos_sh = NamedSharding(mesh, P(b))
        return (full, arena, arena, sc, sc, tbl_sh, pos_sh), (full,)

    def infer(mesh, arg_shapes, shape):
        return shardings(mesh, arg_shapes[0].sharding)[1][0]

    def partition(mesh, arg_shapes, result_shape):
        args, outs = shardings(mesh, arg_shapes[0].sharding)
        return mesh, f, outs[0], args

    _def_partition(
        cp, partition, infer,
        "b h t d, p h s d, p h s d, p h s, p h s, b n, b -> b h t d",
        ("t", "d", "p", "s", "n"))
    return cp


@hot_path
def flash_decode_attention_paged(q, k, v, block_tbl, pos, scale=None):
    """Block-table flash decode over a page arena.

    Args:
      q: [B, H, S, D] query rows at per-row frontiers ``pos``; each
        row's k/v for those positions must already be SCATTERED into
        its pages (models/generation.py writes before attending).
      k, v: [P, H, page_len, D] page arenas (one layer's view of the
        paged pool; page 0 is the trash page freed rows point at).
      block_tbl: [B, n_lp] int32 — row b's logical block j lives in
        arena page ``block_tbl[b, j]``.
      pos: [B] int32 per-row frontiers.
      scale: score scale; default 1/sqrt(D).

    block_k is page_len by construction (kernel blocks == pages), so
    there is no autotuned tile here; page_len must be a multiple of
    BLOCK_MIN for the kernel to engage, and other page sizes take the
    gather + dense-reference fallback (same math).
    Returns: [B, H, S, D] in q.dtype.
    """
    d = q.shape[-1]
    page_len = k.shape[2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if not decode_supported(page_len):
        return decode_attention_paged_reference(q, k, v, block_tbl, pos,
                                                scale=scale)
    if _use_custom_partitioning():
        return _decode_paged_partitioned(float(scale))(
            q, k, v, block_tbl, pos)
    return _flash_decode_paged_pallas(q, k, v, block_tbl, pos,
                                      float(scale))


@hot_path
def flash_decode_attention_paged_q8(q, k, v, k_scale, v_scale, block_tbl,
                                    pos, scale=None):
    """int8 block-table flash decode: ``flash_decode_attention_paged``
    over int8 code arenas with fp32 per-(head, position) scale arenas
    [P, H, page_len], dequantizing in-block exactly like the dense q8
    family."""
    d = q.shape[-1]
    page_len = k.shape[2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if not decode_supported(page_len):
        return decode_attention_paged_q8_reference(
            q, k, v, k_scale, v_scale, block_tbl, pos, scale=scale)
    if _use_custom_partitioning():
        return _decode_paged_q8_partitioned(float(scale))(
            q, k, v, k_scale, v_scale, block_tbl, pos)
    return _flash_decode_paged_q8_pallas(q, k, v, k_scale, v_scale,
                                         block_tbl, pos, float(scale))
