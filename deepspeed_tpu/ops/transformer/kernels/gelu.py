"""Fused bias+GELU — TPU-native equivalent of reference
csrc/transformer/gelu_kernels.cu (gelu_kernel :38, fused_bias_gelu :98,
d_gelu backward :182, launchers :277-335).

One Pallas kernel computes gelu(x + bias) in a single HBM pass; the backward
regenerates the activation derivative from the saved pre-activation (the
reference does the same — it stores the *input* and recomputes tanh in
d_gelu_func). Uses the tanh approximation exactly as the reference does.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_SQRT_2_OVER_PI = 0.7978845608028654


def _interpret():
    return jax.default_backend() != "tpu"


def _gelu_f32(z):
    return 0.5 * z * (1.0 + jnp.tanh(_SQRT_2_OVER_PI * (z + 0.044715 * z ** 3)))


def _d_gelu_f32(z):
    t = jnp.tanh(_SQRT_2_OVER_PI * (z + 0.044715 * z ** 3))
    dt = (1.0 - t * t) * _SQRT_2_OVER_PI * (1.0 + 3 * 0.044715 * z * z)
    return 0.5 * (1.0 + t) + 0.5 * z * dt


def _bias_gelu_kernel(x_ref, b_ref, o_ref):
    z = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = _gelu_f32(z).astype(o_ref.dtype)


def _bias_gelu_fwd(x, bias):
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    n = x2.shape[0]
    rows = max(8, min(n, (2 * 1024 * 1024) // max(1, hidden * 4)))
    while n % rows:
        rows //= 2
    o = pl.pallas_call(
        _bias_gelu_kernel,
        grid=(n // max(rows, 1),),
        in_specs=[pl.BlockSpec((max(rows, 1), hidden), lambda i: (i, 0)),
                  pl.BlockSpec((hidden,), lambda i: (0,))],
        out_specs=pl.BlockSpec((max(rows, 1), hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, hidden), x.dtype),
        interpret=_interpret(),
    )(x2, bias)
    return o.reshape(x.shape)


@jax.custom_vjp
def fused_bias_gelu(x, bias):
    """gelu(x + bias), tanh approximation (reference gelu_kernels.cu:38)."""
    return _bias_gelu_fwd(x, bias)


def _fused_bias_gelu_fwd(x, bias):
    return _bias_gelu_fwd(x, bias), (x, bias)


def _fused_bias_gelu_bwd(res, g):
    x, bias = res
    z = x.astype(jnp.float32) + bias.astype(jnp.float32)
    dz = g.astype(jnp.float32) * _d_gelu_f32(z)
    dx = dz.astype(x.dtype)
    reduce_axes = tuple(range(x.ndim - 1))
    dbias = jnp.sum(dz, axis=reduce_axes).astype(bias.dtype)
    return dx, dbias


fused_bias_gelu.defvjp(_fused_bias_gelu_fwd, _fused_bias_gelu_bwd)


def bias_gelu_reference(x, bias):
    z = x.astype(jnp.float32) + bias.astype(jnp.float32)
    return _gelu_f32(z).astype(x.dtype)
