"""Fused multi-head attention — the TPU-native answer to the reference's
attention pipeline (reference csrc/transformer/ds_transformer_cuda.cpp:624:
qkv GEMM -> head split -> score GEMM -> launch_attn_softmax -> attn dropout
-> ctx GEMM -> head merge).

On GPU the reference fuses softmax/dropout between separate cuBLAS GEMMs,
materialising the [T, T] score matrix. On TPU the right fusion boundary is
different: one flash-style Pallas kernel keeps each score block in VMEM and
never writes the [T, T] matrix to HBM — O(T) memory instead of O(T^2), and
both GEMMs land on the MXU from the same kernel.

Kernel structure (the part that makes it fast). The kernel is VPU-bound,
not MXU-bound — at d=64 the score matrix has 16x more elements than the
q/o blocks, so every elementwise pass over [bq, bk] fp32 scores costs more
than the matmuls. The design therefore minimises score-matrix passes:
- q is PRE-SCALED by 1/sqrt(d) outside the kernel ([T, d] pass instead of
  a [T, T] pass in every kernel);
- the causal mask is a CONSTANT additive tril block passed as an input and
  applied only to diagonal (straddling) blocks — fully-active blocks skip
  masking entirely, fully-masked blocks are skipped by @pl.when and their
  index map clamps to the last useful block (no new DMA for a repeated
  index). Per-block iota/compare/select ladders only remain for the
  uncommon block_q != block_k causal shapes;
- when the kv extent is a single block, the online-softmax machinery
  (running max/sum scratch, accumulator rescale) collapses to one direct
  softmax with no scratch at all;
- the softmax ROW-SUM rides the PV matmul: p @ [v | 1] returns the context
  block and the row-sum from one MXU op, deleting a VPU reduce over
  [bq, bk] (forward);
- in the backward, the delta subtraction rides the dp matmul the same way:
  [dO | -delta] @ [V | 1]^T produces dp - delta directly (fp32 MXU
  accumulation), deleting another [bq, bk] VPU pass;
- in low-precision models the [bq, bk] exp runs in the model dtype (half
  the vector elements per VPU op) and dp - delta is emitted in the model
  dtype, so ds = p * dpd is a pure low-precision multiply; fp32 models
  keep fully-fp32 intermediates (parity tests pin this);
- matmul inputs stay in the model dtype (bf16) with fp32 MXU accumulation
  (preferred_element_type); softmax statistics and accumulators live in
  fp32 VMEM scratch across grid steps;
- in the backward, the 1/sqrt(d) factor on dq is applied to the [T, d]
  OUTPUT (dk/dv need no factor at all with pre-scaled q), never to the
  [T, T] ds matrix.

Forward: online-softmax accumulation over key/value blocks.
Backward: standard two-pass flash backward (one kernel produces dq looping
over kv blocks; one produces dk/dv looping over q blocks), using the saved
per-row logsumexp; wired up with jax.custom_vjp.

Off-TPU the kernels run in Pallas interpret mode, so the CPU test mesh
exercises the same code path (tests mirror reference
tests/unit/test_cuda_forward.py / test_cuda_backward.py grids).
"""

import contextlib
import functools
import os
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30
# Lane width for the fp32 softmax-statistic scratch rows: Mosaic pads
# second-minor×minor tiles to (8, 128), so statistics are kept broadcast
# across a full 128-lane row instead of a width-1 column.
_STATS_LANES = 128


def _interpret():
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Reference (pure jnp) implementation — ground truth for parity tests and
# fallback for shapes the kernel does not support.
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, mask=None, causal=False, scale=None,
                  return_lse=False, precision=None):
    """q,k,v: [B, H, T, D]; mask: additive [B, T_kv] (broadcast over heads
    and query rows, the BERT padding-mask shape). With return_lse, also
    returns the per-row logsumexp [B, H, T, 1] fp32 (the ragged fallback
    of flash_attention_with_lse shares this single dense implementation).

    precision: forwarded to the two einsums. When None, low-precision
    inputs keep the MXU DEFAULT (a single bf16-input pass — fast, and
    consistent with the recompute in ring attention's dense backward, so
    fwd/bwd rounding cancels) while fp32 inputs contract at HIGHEST: at
    DEFAULT the MXU rounds fp32 operands to bf16, which would make both
    the fp32 production fallback lossy and a parity oracle LESS accurate
    than the kernel under test (the kernel applies the same rule)."""
    if precision is None:
        precision = _mxu_precision(q.dtype)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32), precision=precision) * scale
    if mask is not None:
        s = s + mask[:, None, None, :].astype(jnp.float32)
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        cm = jnp.tril(jnp.ones((t_q, t_k), dtype=bool))
        s = jnp.where(cm[None, None], s, NEG_INF)
    # Normalize by DIVISION, not exp(s - lse): at mask magnitudes (-1e9)
    # fp32 loses log-sum bits in lse (-1e9 + log2 rounds back to -1e9), so
    # exp(s - lse) silently denormalizes fully-masked rows. Division keeps
    # the row sum exact — the same stability structure as the flash kernel.
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    l = jnp.sum(e, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", e / l, v.astype(jnp.float32),
                   precision=precision).astype(q.dtype)
    if return_lse:
        return o, m + jnp.log(l)
    return o


def _last_kv_block(iq, block_q, block_k):
    """Index of the last key block a causal query block iq attends to."""
    return ((iq + 1) * block_q - 1) // block_k


def _first_q_block(jk, block_q, block_k):
    """Index of the first query block that attends to causal key block jk."""
    return (jk * block_k) // block_q


def _tril_block(block_q, block_k):
    """Constant additive causal mask for a diagonal block (bq == bk).
    Built from iota primitives (not a materialized array) so functions
    passing it stay const-free — custom_partitioning requires closed
    jaxprs; XLA folds it to a constant anyway."""
    r = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return jnp.where(r >= c, jnp.float32(0.0), jnp.float32(NEG_INF))


def _is_lowp(dtype):
    return jnp.dtype(dtype) in (jnp.bfloat16, jnp.float16)


def _mxu_precision(dtype):
    """Dot precision for the kernel's MXU contractions, by model dtype.

    At DEFAULT precision the MXU rounds fp32 operands to bf16 — fine for
    low-precision models (operands already are bf16/fp16), but it silently
    costs fp32 models ~1e-2 parity now that the softmax row-sum and the
    `dp - delta` correction ride the matmuls (the denominator inherits p's
    operand rounding; seen live on v5e: 9e-3 fwd error vs a
    precision-highest oracle). fp32 is the parity/debug path, so it takes
    HIGHEST (multi-pass MXU, ~fp32-exact) and keeps the fusions."""
    return None if _is_lowp(dtype) else jax.lax.Precision.HIGHEST


def _exp_lowp(t, dtype):
    """exp over a [bq, bk] block — the widest VPU pass in the kernel.

    Low-precision models run the exp in the model dtype: half the vector
    elements per VPU op, and the result feeds the next matmul without a
    cast pass. Absolute error is ~p * |t| * 2^-8 <= e^-1 * 2^-8 relative
    to the row total — the same order as the fp32-exp-then-cast-to-bf16 it
    replaces. fp32 models keep the fp32 exp (parity tests pin 1e-4)."""
    if _is_lowp(dtype):
        return jnp.exp(t.astype(dtype))
    return jnp.exp(t)


def _pv_rowsum(p, v_blk):
    """p @ [v | 1] on the MXU: one matmul returns both the context block
    [bq, d] and the softmax row-sum [bq, 1], deleting a VPU reduce over
    [bq, bk]. The row-sum shares p's rounding with the context numerator,
    so o = pv / l normalizes exactly the values it summed."""
    d = v_blk.shape[1]
    v_ext = jnp.concatenate(
        [v_blk, jnp.ones((v_blk.shape[0], 1), v_blk.dtype)], axis=1)
    pv_ext = jax.lax.dot_general(p.astype(v_blk.dtype), v_ext,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=_mxu_precision(v_blk.dtype))
    return pv_ext[:, :d], pv_ext[:, d:d + 1]


def _dp_minus_delta(do, v_blk, delta):
    """[dO | -delta] @ [V | 1]^T on the MXU: the delta subtraction rides
    the dp matmul (fp32 accumulation inside the MXU) instead of costing a
    VPU pass over [bq, bk]. Low-precision models split the fp32 delta into
    hi+lo model-dtype COLUMNS (~16 mantissa bits through the MXU): rows
    with concentrated attention have dp ~ delta and p ~ 1, so a single
    bf16 delta column's 2^-8 rounding would surface at full scale in
    ds = p * (dp - delta). The output is emitted in the model dtype — its
    rounding is relative to the (small) difference, not to delta — making
    ds a pure low-precision multiply.

    Only bf16 takes the fused columns: bf16 shares fp32's exponent range,
    so the delta split never overflows. fp16 does NOT — under dynamic loss
    scaling delta = rowsum(dO * O) routinely exceeds fp16 max (65504) even
    when every dO element fits, and an inf hi column would turn the MXU
    accumulation into NaN — so fp16 keeps the classic fp32 subtract. fp32
    models ride an exact fp32 delta column (exact parity)."""
    dtype = v_blk.dtype
    if jnp.dtype(dtype) == jnp.bfloat16:
        d_hi = delta.astype(dtype)
        d_lo = (delta - d_hi.astype(jnp.float32)).astype(dtype)
        do_ext = jnp.concatenate([do.astype(dtype), -d_hi, -d_lo], axis=1)
        ones = jnp.ones((v_blk.shape[0], 2), dtype)
        v_ext = jnp.concatenate([v_blk, ones], axis=1)
        # Mosaic requires the MXU accumulator to be 32-bit (a bf16
        # preferred_element_type fails verification), so accumulate in
        # fp32 and cast on emit — same rounding contract: the cast error
        # is relative to the small difference, not to delta.
        out = jax.lax.dot_general(do_ext, v_ext, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return out.astype(dtype)
    if _is_lowp(dtype):  # fp16: unfused fp32 subtract (overflow-safe)
        dp = jax.lax.dot_general(do.astype(dtype), v_blk,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        return dp - delta
    do_ext = jnp.concatenate(
        [do.astype(dtype), (-delta).astype(dtype)], axis=1)
    v_ext = jnp.concatenate(
        [v_blk, jnp.ones((v_blk.shape[0], 1), dtype)], axis=1)
    return jax.lax.dot_general(do_ext, v_ext, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=_mxu_precision(dtype))


def _apply_causal(s, iq, j, block_q, block_k, tril_ref):
    """Apply the causal mask to score block (iq, j). With bq == bk only the
    diagonal block straddles the boundary, so the constant tril input is
    added under @pl.when; otherwise fall back to the iota ladder."""
    if tril_ref is not None:
        return jax.lax.cond(iq == j, lambda: s + tril_ref[...], lambda: s)
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 0)
    k_pos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, causal, block_q, block_k, has_mask, has_tril,
                single_kv):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    idx = 3
    mask_ref = tril_ref = None
    if has_mask:
        mask_ref = refs[idx]
        idx += 1
    if has_tril:
        tril_ref = refs[idx]
        idx += 1
    o_ref, lse_ref = refs[idx:idx + 2]
    scratch = refs[idx + 2:]

    iq = pl.program_id(2)
    j = pl.program_id(3)
    n_kv = pl.num_programs(3)

    def scores():
        s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_mxu_precision(q_ref.dtype))
        if mask_ref is not None:
            s = s + mask_ref[0][None, :]
        if causal:
            s = _apply_causal(s, iq, j, block_q, block_k, tril_ref)
        return s

    if single_kv:
        # One kv block: direct softmax, no scratch, no rescale passes.
        s = scores()
        m = jnp.max(s, axis=-1, keepdims=True)
        p = _exp_lowp(s - m, o_ref.dtype)
        pv, l = _pv_rowsum(p, v_ref[0, 0])
        l = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (pv / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m + jnp.log(l)
        return

    acc, m_s, l_s = scratch

    @pl.when(j == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    if causal:
        active = j <= _last_kv_block(iq, block_q, block_k)
    else:
        active = j < n_kv

    @pl.when(active)
    def _compute():
        s = scores()
        m_prev = m_s[:, 0:1]                               # [bq, 1]
        l_prev = l_s[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = _exp_lowp(s - m_new, o_ref.dtype)              # [bq, bk]
        pv, l_cur = _pv_rowsum(p, v_ref[0, 0])
        l_new = alpha * l_prev + l_cur
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)
        acc[...] = acc[...] * alpha + pv

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = jnp.maximum(l_s[:, 0:1], 1e-30)
        o_ref[0, 0] = (acc[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_s[:, 0:1] + jnp.log(l)


def _flash_fwd_pallas(q, k, v, mask, scale, causal, block_q, block_k):
    from jax.experimental.pallas import tpu as pltpu

    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_kv)
    n_kv = pl.cdiv(t_kv, block_k)
    grid = (b, h, pl.cdiv(t_q, block_q), n_kv)
    # Pre-scale q: one [T, d] pass replaces a [T, T] pass per kernel.
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    use_tril = causal and block_q == block_k
    single_kv = n_kv == 1

    if causal:
        def kv_index(b_, h_, i, j):
            # Clamp past-diagonal blocks to the last useful one: a repeated
            # block index issues no new DMA, and @pl.when skips the compute.
            return (b_, h_, jnp.minimum(j, _last_kv_block(i, block_q, block_k)), 0)
    else:
        def kv_index(b_, h_, i, j):
            return (b_, h_, j, 0)

    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        pl.BlockSpec((1, 1, block_k, d), kv_index),
        pl.BlockSpec((1, 1, block_k, d), kv_index),
    ]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(
            pl.BlockSpec((1, block_k), lambda b_, h_, i, j: (b_, kv_index(b_, h_, i, j)[2])))
        args.append(mask.astype(jnp.float32))
    if use_tril:
        in_specs.append(
            pl.BlockSpec((block_q, block_k), lambda b_, h_, i, j: (0, 0)))
        args.append(_tril_block(block_q, block_k))

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal,
                          block_q=block_q, block_k=block_k,
                          has_mask=mask is not None, has_tril=use_tril,
                          single_kv=single_kv),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t_q, 1), jnp.float32),
        ],
        scratch_shapes=[] if single_kv else [
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------
# delta_i = rowsum(dO_i * O_i); then with q_s = q/sqrt(d):
#   s = q_s K^T,  P = exp(s - lse),  dP = dO V^T,  dS = P * (dP - delta)
#   dq = (dS K) / sqrt(d),  dk = dS^T q_s,  dv = P^T dO
# P is recomputed blockwise from q_s, k and the saved lse (never stored).

def _bwd_unpack(refs, has_mask, has_tril, n_out):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    idx = 3
    mask_ref = tril_ref = None
    if has_mask:
        mask_ref = refs[idx]
        idx += 1
    if has_tril:
        tril_ref = refs[idx]
        idx += 1
    do_ref, lse_ref, delta_ref = refs[idx:idx + 3]
    idx += 3
    outs = refs[idx:idx + n_out]
    scratch = refs[idx + n_out:]
    return (q_ref, k_ref, v_ref, mask_ref, tril_ref, do_ref, lse_ref,
            delta_ref, outs, scratch)


def _bwd_scores(q_ref, k_ref, mask_ref, tril_ref, iq, j, causal,
                block_q, block_k):
    s = jax.lax.dot_general(q_ref[0, 0], k_ref[0, 0],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=_mxu_precision(q_ref.dtype))
    if mask_ref is not None:
        s = s + mask_ref[0][None, :]
    if causal:
        s = _apply_causal(s, iq, j, block_q, block_k, tril_ref)
    return s


def _bwd_dq_kernel(*refs, scale, causal, block_q, block_k, has_mask,
                   has_tril, single_kv):
    (q_ref, k_ref, v_ref, mask_ref, tril_ref, do_ref, lse_ref, delta_ref,
     (dq_ref,), scratch) = _bwd_unpack(refs, has_mask, has_tril, 1)

    iq = pl.program_id(2)
    j = pl.program_id(3)
    n_kv = pl.num_programs(3)

    def ds_block():
        s = _bwd_scores(q_ref, k_ref, mask_ref, tril_ref, iq, j, causal,
                        block_q, block_k)
        # s <= lse mathematically; clamping guards fully-masked rows where
        # fp32 lse (~mask magnitude, ulp 64) loses the log-sum bits and a
        # spurious positive exponent would poison the step with inf grads.
        p = _exp_lowp(jnp.minimum(s - lse_ref[0, 0], 0.0), dq_ref.dtype)
        dpd = _dp_minus_delta(do_ref[0, 0], v_ref[0, 0], delta_ref[0, 0])
        ds = (p * dpd).astype(k_ref.dtype)
        return jax.lax.dot_general(ds, k_ref[0, 0], (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32,
                                   precision=_mxu_precision(k_ref.dtype))

    if single_kv:
        dq_ref[0, 0] = (ds_block() * scale).astype(dq_ref.dtype)
        return

    (dq_acc,) = scratch

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if causal:
        active = j <= _last_kv_block(iq, block_q, block_k)
    else:
        active = j < n_kv

    @pl.when(active)
    def _compute():
        dq_acc[...] += ds_block()

    @pl.when(j == n_kv - 1)
    def _finalize():
        dq_ref[0, 0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, causal, block_q, block_k, has_mask, has_tril,
                    single_q):
    (q_ref, k_ref, v_ref, mask_ref, tril_ref, do_ref, lse_ref, delta_ref,
     (dk_ref, dv_ref), scratch) = _bwd_unpack(refs, has_mask, has_tril, 2)

    jk = pl.program_id(2)
    i = pl.program_id(3)
    n_q = pl.num_programs(3)

    def grads_block():
        s = _bwd_scores(q_ref, k_ref, mask_ref, tril_ref, i, jk, causal,
                        block_q, block_k)
        # s <= lse mathematically; clamping guards fully-masked rows where
        # fp32 lse (~mask magnitude, ulp 64) loses the log-sum bits and a
        # spurious positive exponent would poison the step with inf grads.
        p = _exp_lowp(jnp.minimum(s - lse_ref[0, 0], 0.0), dk_ref.dtype)
        do = do_ref[0, 0]
        dv = jax.lax.dot_general(p.astype(do.dtype), do,
                                 (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=_mxu_precision(do.dtype))
        dpd = _dp_minus_delta(do, v_ref[0, 0], delta_ref[0, 0])
        ds = (p * dpd).astype(q_ref.dtype)
        dk = jax.lax.dot_general(ds, q_ref[0, 0], (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=_mxu_precision(q_ref.dtype))
        return dk, dv

    if single_q:
        if causal:
            # A kv block entirely past the query extent (t_kv > t_q) gets
            # no probability mass — the diagonal tril only covers i == jk,
            # so these blocks must be zeroed explicitly (the multi-block
            # path's `active` guard; verified by the t_q<t_kv grad test).
            active = i >= _first_q_block(jk, block_q, block_k)

            @pl.when(active)
            def _write():
                dk, dv = grads_block()
                dk_ref[0, 0] = dk.astype(dk_ref.dtype)
                dv_ref[0, 0] = dv.astype(dv_ref.dtype)

            @pl.when(jnp.logical_not(active))
            def _zero():
                dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
                dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])
        else:
            dk, dv = grads_block()
            dk_ref[0, 0] = dk.astype(dk_ref.dtype)
            dv_ref[0, 0] = dv.astype(dv_ref.dtype)
        return

    dk_acc, dv_acc = scratch

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if causal:
        active = i >= _first_q_block(jk, block_q, block_k)
    else:
        active = i < n_q

    @pl.when(active)
    def _compute():
        dk, dv = grads_block()
        dk_acc[...] += dk
        dv_acc[...] += dv

    @pl.when(i == n_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(*refs, scale, causal, block_q, block_k, has_mask,
                      has_tril):
    """One-pass backward: dq, dk, dv from a single sweep over (i, j) block
    pairs. The split kernels each recompute s, p and dO.V^T per pair —
    7 score-sized matmuls + 2 exp passes per pair total; this kernel does
    5 matmuls + 1 exp (the MXU-ideal count), with k/v resident in VMEM per
    (b, h) and full-length fp32 dk/dv accumulators in scratch. It also
    reads k and v from HBM once per (b, h) instead of once per q block."""
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    idx = 3
    mask_ref = tril_ref = None
    if has_mask:
        mask_ref = refs[idx]
        idx += 1
    if has_tril:
        tril_ref = refs[idx]
        idx += 1
    do_ref, lse_ref, delta_ref = refs[idx:idx + 3]
    dq_ref, dk_ref, dv_ref = refs[idx + 3:idx + 6]
    dk_acc, dv_acc = refs[idx + 6:idx + 8]

    i = pl.program_id(2)
    n_q = pl.num_programs(2)
    t_kv = k_ref.shape[2]
    n_kv = t_kv // block_k
    d = q_ref.shape[-1]
    prec = _mxu_precision(q_ref.dtype)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_blk = q_ref[0, 0]
    do_blk = do_ref[0, 0]
    lse_blk = lse_ref[0, 0]
    delta_blk = delta_ref[0, 0]

    def body(j, dq_local):
        kv = pl.ds(j * block_k, block_k)
        k_blk = k_ref[0, 0, kv]
        v_blk = v_ref[0, 0, kv]
        s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=prec)
        if mask_ref is not None:
            s = s + mask_ref[0, kv][None, :]
        if causal:
            s = _apply_causal(s, i, j, block_q, block_k, tril_ref)
        # s <= lse mathematically; the clamp guards fully-masked rows
        # (same contract as the split kernels).
        p = _exp_lowp(jnp.minimum(s - lse_blk, 0.0), dq_ref.dtype)
        dv_acc[kv] += jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dpd = _dp_minus_delta(do_blk, v_blk, delta_blk)
        ds = (p * dpd).astype(k_ref.dtype)
        dk_acc[kv] += jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        return dq_local + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)

    if causal:
        n_j = jnp.minimum(_last_kv_block(i, block_q, block_k) + 1, n_kv)
    else:
        n_j = n_kv
    dq_local = jax.lax.fori_loop(
        0, n_j, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0, 0] = (dq_local * scale).astype(dq_ref.dtype)

    @pl.when(i == n_q - 1)
    def _emit():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


# Scoped-VMEM budget for the fused backward's TOTAL estimated footprint
# (resident k/v/dk/dv + fp32 accumulators + live [block_q, block_k]
# loop intermediates + double-buffered q-side blocks). The hardware
# limit is ~16 MB/core; 12 MB leaves headroom for Mosaic's own stack
# slop. Measured live (v5e, r5): 1024x1024 tiles stack-OOMed at 20.82 MB
# vs the 16 MB limit — the old resident-only estimate missed the ~16 MB
# of score-sized intermediates entirely. Overridable for experiments.
_FUSED_BWD_VMEM_BUDGET = int(os.environ.get(
    "DS_TPU_FUSED_BWD_MAX_BYTES", 12 * 1024 * 1024))

# Resident-only gate used by _bwd_mode for callers that cannot shrink
# tiles (the block-sparse fused backward keeps full-length k/v/dk/dv
# resident and layouts its own loop blocks): defaults to the pre-r5 6 MB
# so the larger total-footprint default above does not silently admit
# sparse shapes whose resident set alone crowds out the loop
# intermediates — but an EXPLICIT DS_TPU_FUSED_BWD_MAX_BYTES keeps its
# historical power to admit larger resident sets.
_RESIDENT_BWD_VMEM_BUDGET = (
    int(os.environ["DS_TPU_FUSED_BWD_MAX_BYTES"])
    if "DS_TPU_FUSED_BWD_MAX_BYTES" in os.environ else 6 * 1024 * 1024)


def _fused_bwd_vmem_bytes(t_kv, d, dtype, block_q, block_k, causal):
    """Estimated scoped-VMEM footprint of one fused-backward program
    instance. Counts what the kernel actually keeps live (see
    _bwd_fused_kernel): resident k/v + dk/dv outputs (model dtype) and
    two full-length fp32 accumulators; per-loop [block_q, block_k]
    intermediates — s and dpd in fp32, p and ds in the model dtype —
    plus the fp32 tril block when causal uses equal tiles; and the
    double-buffered streamed q/do/dq blocks."""
    itemsize = jnp.dtype(dtype).itemsize
    resident = t_kv * d * (4 * itemsize + 2 * 4)
    per_elem = 2 * 4 + 2 * itemsize + \
        (4 if causal and block_q == block_k else 0)
    streamed = 2 * 3 * block_q * d * itemsize
    return resident + block_q * block_k * per_elem + streamed


def _fit_fused_bwd_tiles(t_kv, d, dtype, block_q, block_k, causal):
    """Largest (block_q, block_k) <= the requested tiles whose estimated
    footprint fits the budget, halving the larger side first (both sides
    stay >= 128 and keep dividing the sequence since the requested tiles
    do and only halving happens). None if nothing fits."""
    bq, bk = block_q, block_k
    while _fused_bwd_vmem_bytes(t_kv, d, dtype, bq, bk, causal) > \
            _FUSED_BWD_VMEM_BUDGET:
        if max(bq, bk) <= 128:
            return None
        if bq >= bk and bq > 128:
            bq //= 2
        else:
            bk //= 2
    return bq, bk


@functools.lru_cache(maxsize=None)
def _fused_bwd_supported():
    """One-time probe: does this backend compile the fused backward's
    dynamic-offset VMEM scratch accumulation? On a Mosaic version that
    rejects the pattern, 'auto' must degrade to the split kernels instead
    of failing every training step. Concrete tiny-shape call, so it is
    safe to run even while an outer trace is in progress; off-TPU
    (interpret mode) the semantics are test-covered, return True."""
    if jax.default_backend() != "tpu":
        return True
    try:
        b, h, t, d = 1, 1, 256, 128
        z = jnp.zeros((b, h, t, d), jnp.bfloat16)
        row = jnp.zeros((b, h, t, 1), jnp.float32)
        out = _flash_bwd_fused_pallas(z, z, z, None, row, row, z,
                                      scale=1.0, causal=True,
                                      block_q=128, block_k=128)
        jax.block_until_ready(out)
        return True
    except Exception as e:  # compile/verification failure — not data
        import warnings
        warnings.warn("fused flash backward unsupported on this backend "
                      "({}); auto mode falls back to the split kernels"
                      .format(str(e)[:500]))
        return False


def _bwd_mode(t_kv, d, dtype):
    """'fused' or 'split' — env DS_TPU_FLASH_BWD overrides the VMEM fit.
    Governs both the dense flash backward and the block-sparse one
    (ops/sparse_attention/kernels.py), which share the kernel structure."""
    mode = os.environ.get("DS_TPU_FLASH_BWD", "auto")
    if mode in ("fused", "split"):
        return mode
    itemsize = jnp.dtype(dtype).itemsize
    resident = t_kv * d * (4 * itemsize + 2 * 4)
    if resident > _RESIDENT_BWD_VMEM_BUDGET:
        return "split"
    return "fused" if _fused_bwd_supported() else "split"


def _flash_bwd_fused_pallas(q, k, v, mask, delta, lse, do, scale, causal,
                            block_q, block_k):
    from jax.experimental.pallas import tpu as pltpu

    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    n_q = pl.cdiv(t_q, block_q)
    use_tril = causal and block_q == block_k

    q_spec = pl.BlockSpec((1, 1, block_q, d),
                          lambda b_, h_, i: (b_, h_, i, 0))
    kv_full = pl.BlockSpec((1, 1, t_kv, d), lambda b_, h_, i: (b_, h_, 0, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda b_, h_, i: (b_, h_, i, 0))

    in_specs = [q_spec, kv_full, kv_full]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, t_kv), lambda b_, h_, i: (b_, 0)))
        args.append(mask.astype(jnp.float32))
    if use_tril:
        in_specs.append(
            pl.BlockSpec((block_q, block_k), lambda b_, h_, i: (0, 0)))
        args.append(_tril_block(block_q, block_k))
    in_specs += [q_spec, row_spec, row_spec]
    args += [do, lse, delta]

    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          has_mask=mask is not None, has_tril=use_tril),
        grid=(b, h, n_q),
        in_specs=in_specs,
        out_specs=[q_spec, kv_full, kv_full],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((t_kv, d), jnp.float32),
                        pltpu.VMEM((t_kv, d), jnp.float32)],
        interpret=_interpret(),
    )(*args)
    # Tuple, not pallas_call's list: the custom_partitioning wrapper
    # declares tuple outputs and jax's out-tree flattening is
    # container-type strict.
    return dq, dk, dv


def _flash_bwd_pallas(q, k, v, mask, delta, lse, g, scale, causal, block_q,
                      block_k):
    """delta: [B, H, T, 1] fp32 = rowsum(dO * O) (minus any lse cotangent —
    see _flash_attention_lse); computed by the caller so this function stays
    const-free and delta-shifts need no new partitioning variant."""
    from jax.experimental.pallas import tpu as pltpu

    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_kv)
    n_q = pl.cdiv(t_q, block_q)
    n_kv = pl.cdiv(t_kv, block_k)
    do = g
    # Same pre-scaled q as the forward (so the recomputed P matches the
    # saved lse); dk needs no correction, dq is rescaled on its output.
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    if _bwd_mode(t_kv, d, q.dtype) == "fused":
        if os.environ.get("DS_TPU_FLASH_BWD") == "fused":
            # Explicitly forced: honor the request AND its exact tiles —
            # an A/B experiment must measure the configured tiling, not
            # a silently substituted one.
            return _flash_bwd_fused_pallas(q, k, v, mask, delta, lse, do,
                                           scale, causal, block_q, block_k)
        # The forward's (autotuned) tiles can be too big for the fused
        # backward's larger live set — shrink just the backward's tiles
        # to the VMEM fit rather than abandoning the one-pass kernel
        # (measured live: 1024x1024 stack-OOMed the 16 MB scoped limit).
        fit = _fit_fused_bwd_tiles(t_kv, d, q.dtype, block_q, block_k,
                                   causal)
        if fit is not None:
            return _flash_bwd_fused_pallas(q, k, v, mask, delta, lse, do,
                                           scale, causal, fit[0], fit[1])
    use_tril = causal and block_q == block_k
    tril = _tril_block(block_q, block_k) if use_tril else None

    # dq: grid over (q block, kv block), kv innermost and pipelined.
    if causal:
        def kv_index(b_, h_, i, j):
            return (b_, h_, jnp.minimum(j, _last_kv_block(i, block_q, block_k)), 0)
    else:
        def kv_index(b_, h_, i, j):
            return (b_, h_, j, 0)
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d), kv_index)
    row_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    tril_spec = pl.BlockSpec((block_q, block_k), lambda b_, h_, i, j: (0, 0))

    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(
            pl.BlockSpec((1, block_k), lambda b_, h_, i, j: (b_, kv_index(b_, h_, i, j)[2])))
        args.append(mask.astype(jnp.float32))
    if use_tril:
        in_specs.append(tril_spec)
        args.append(tril)
    in_specs += [q_spec, row_spec, row_spec]
    args += [do, lse, delta]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          has_mask=mask is not None, has_tril=use_tril,
                          single_kv=n_kv == 1),
        grid=(b, h, n_q, n_kv),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[] if n_kv == 1 else
        [pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*args)

    # dk/dv: grid over (kv block, q block), q innermost and pipelined.
    if causal:
        def q_index(b_, h_, jk, i):
            # Clamp into the valid block range: fully-inactive kv blocks
            # (first active q block past the end) skip compute, so reading
            # the last block instead issues no stray DMA.
            first = jnp.minimum(_first_q_block(jk, block_q, block_k),
                                n_q - 1)
            return (b_, h_, jnp.maximum(i, first), 0)
    else:
        def q_index(b_, h_, jk, i):
            return (b_, h_, i, 0)
    q_spec2 = pl.BlockSpec((1, 1, block_q, d), q_index)
    kv_spec2 = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, jk, i: (b_, h_, jk, 0))
    row_spec2 = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b_, h_, jk, i: (b_, h_, q_index(b_, h_, jk, i)[2], 0))

    in_specs = [q_spec2, kv_spec2, kv_spec2]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, block_k), lambda b_, h_, jk, i: (b_, jk)))
        args.append(mask.astype(jnp.float32))
    if use_tril:
        in_specs.append(tril_spec)
        args.append(tril)
    in_specs += [q_spec2, row_spec2, row_spec2]
    args += [do, lse, delta]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal,
                          block_q=block_q, block_k=block_k,
                          has_mask=mask is not None, has_tril=use_tril,
                          single_q=n_q == 1),
        grid=(b, h, n_kv, n_q),
        in_specs=in_specs,
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[] if n_q == 1 else
        [pltpu.VMEM((block_k, d), jnp.float32),
         pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_interpret(),
    )(*args)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# GSPMD integration — batch/head-parallel partitioning of the kernels.
#
# XLA's SPMD partitioner cannot see inside a pallas_call: without a rule it
# replicates the operands ("involuntary full rematerialization"), turning
# data-parallel attention into a full all-gather per step. The kernels are
# embarrassingly parallel over batch and heads, so custom_partitioning
# declares exactly that: b/h follow the operand sharding, sequence and
# head-dim are replicated (for both the GSPMD callback API and the Shardy
# einsum rule). Each shard then runs the plain pallas kernel on its local
# [b/dp, h/mp, T, D] block. This is the TPU analogue of the reference's
# data-parallel engine wrapping its CUDA kernels (engine.py:508-528 —
# kernels see local tensors, the framework owns the distribution).
# ---------------------------------------------------------------------------


_shard_local = threading.local()


@contextlib.contextmanager
def shard_local_kernels():
    """Within this context, flash entry points skip the
    custom_partitioning wrapper and launch the raw pallas kernels —
    for callers that are ALREADY inside a manual-sharding region
    (shard_map), where every array is shard-local and GSPMD has nothing
    to partition (custom_partitioning is not usable there). Thread-local
    and re-entrant; only tracing cares."""
    prev = getattr(_shard_local, "on", False)
    _shard_local.on = True
    try:
        yield
    finally:
        _shard_local.on = prev


def _use_custom_partitioning():
    return os.environ.get("DS_TPU_NO_CUSTOM_PARTITION", "0") != "1" \
        and not getattr(_shard_local, "on", False)


def _bh_spec(sharding):
    """(batch, head) partition entries of an operand sharding, or (None,
    None) when unknown/unsharded."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return (None, None)
    spec = tuple(spec) + (None,) * (4 - len(spec))
    return spec[0], spec[1]


def _def_partition(cp, partition, infer, rule, factors):
    """def_partition across jax versions: newer jax accepts a Shardy
    ``sharding_rule`` (+ ``need_replication_factors``); jax 0.4.37's
    def_partition takes neither and relies on the GSPMD callbacks alone.
    Feature-detect so the same wrapper works on both."""
    import inspect
    params = inspect.signature(
        custom_partitioning.def_partition).parameters
    kw = {}
    if "sharding_rule" in params:
        kw["sharding_rule"] = rule
        if "need_replication_factors" in params:
            kw["need_replication_factors"] = factors
    cp.def_partition(partition=partition,
                     infer_sharding_from_operands=infer, **kw)


def _cp_wrap(fn, n_in, n_out, rule, mask_pos=None):
    """Wrap fn (shard-local pallas launcher) in custom_partitioning with
    b/h-parallel shardings. Inputs/outputs are [B, H, ...] except an
    optional [B, T_kv] mask at mask_pos; lse outputs are [B, H, T, 1]."""
    cp = custom_partitioning(fn)

    def shardings(mesh, q_sharding):
        b, h = _bh_spec(q_sharding)
        full = NamedSharding(mesh, P(b, h, None, None))
        mask_sh = NamedSharding(mesh, P(b, None))
        args = tuple(full if i != mask_pos else mask_sh
                     for i in range(n_in))
        outs = (full,) * n_out
        return args, outs

    def infer(mesh, arg_shapes, shape):
        _, outs = shardings(mesh, arg_shapes[0].sharding)
        return outs if n_out > 1 else outs[0]

    def partition(mesh, arg_shapes, result_shape):
        args, outs = shardings(mesh, arg_shapes[0].sharding)
        return mesh, fn, (outs if n_out > 1 else outs[0]), args

    # Factors ordered by first appearance in the rule (Shardy requires
    # sorted factor indices): t then d (from q), s (from k), u (from lse).
    _def_partition(cp, partition, infer, rule, ("t", "d", "s", "u"))
    return cp


@functools.lru_cache(maxsize=None)
def _fwd_partitioned(has_mask, scale, causal, block_q, block_k):
    if has_mask:
        def f(q, k, v, mask):
            return _flash_fwd_pallas(q, k, v, mask, scale, causal,
                                     block_q, block_k)
        rule = "b h t d, b h s d, b h s d, b s -> b h t d, b h t u"
        return _cp_wrap(f, 4, 2, rule, mask_pos=3)

    def f(q, k, v):
        return _flash_fwd_pallas(q, k, v, None, scale, causal,
                                 block_q, block_k)
    rule = "b h t d, b h s d, b h s d -> b h t d, b h t u"
    return _cp_wrap(f, 3, 2, rule)


@functools.lru_cache(maxsize=None)
def _bwd_partitioned(has_mask, scale, causal, block_q, block_k):
    if has_mask:
        def f(q, k, v, mask, delta, lse, do):
            return _flash_bwd_pallas(q, k, v, mask, delta, lse, do, scale,
                                     causal, block_q, block_k)
        rule = ("b h t d, b h s d, b h s d, b s, b h t u, b h t u, b h t d "
                "-> b h t d, b h s d, b h s d")
        return _cp_wrap(f, 7, 3, rule, mask_pos=3)

    def f(q, k, v, delta, lse, do):
        return _flash_bwd_pallas(q, k, v, None, delta, lse, do, scale,
                                 causal, block_q, block_k)
    rule = ("b h t d, b h s d, b h s d, b h t u, b h t u, b h t d "
            "-> b h t d, b h s d, b h s d")
    return _cp_wrap(f, 6, 3, rule)


def _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k, raw=False):
    if not raw and _use_custom_partitioning():
        f = _fwd_partitioned(mask is not None, scale, causal,
                             block_q, block_k)
        args = (q, k, v) if mask is None else (q, k, v, mask)
        return f(*args)
    return _flash_fwd_pallas(q, k, v, mask, scale, causal, block_q, block_k)


def _flash_bwd(res, g, scale, causal, block_q, block_k, dlse=None,
               raw=False):
    q, k, v, mask, o, lse = res
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    if dlse is not None:
        # An lse cotangent folds into the same kernels: dlse_i/ds_ij = p_ij,
        # so ds = p * (dp - (delta - dlse)) — a pure delta shift.
        delta = delta - dlse
    if not raw and _use_custom_partitioning():
        f = _bwd_partitioned(mask is not None, scale, causal,
                             block_q, block_k)
        args = (q, k, v, delta, lse, g) if mask is None else \
            (q, k, v, mask, delta, lse, g)
        dq, dk, dv = f(*args)
    else:
        dq, dk, dv = _flash_bwd_pallas(q, k, v, mask, delta, lse, g, scale,
                                       causal, block_q, block_k)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dmask


# ---------------------------------------------------------------------------
# Public entry point
# ---------------------------------------------------------------------------

# ``raw`` (shard-local) is a STATIC nondiff arg captured at the public
# entry: the custom_vjp backward is traced lazily at transpose time —
# possibly after the shard_local_kernels context has exited — so the
# decision must ride the residual-free static args, not the thread-local.
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention(q, k, v, mask, scale, causal, block_q, block_k, raw):
    o, _ = _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k,
                      raw=raw)
    return o


def _flash_attention_fwd(q, k, v, mask, scale, causal, block_q, block_k,
                         raw):
    o, lse = _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k,
                        raw=raw)
    return o, (q, k, v, mask, o, lse)


def _flash_attention_bwd(scale, causal, block_q, block_k, raw, res, g):
    return _flash_bwd(res, g, scale, causal, block_q, block_k, raw=raw)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_lse(q, k, v, mask, scale, causal, block_q, block_k,
                         raw):
    """(o, lse) variant — lse is differentiable too (ring attention merges
    partial results through it)."""
    return _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k,
                      raw=raw)


def _flash_attention_lse_fwd(q, k, v, mask, scale, causal, block_q,
                             block_k, raw):
    o, lse = _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k,
                        raw=raw)
    return (o, lse), (q, k, v, mask, o, lse)


def _flash_attention_lse_bwd(scale, causal, block_q, block_k, raw, res, g):
    do, dlse = g
    return _flash_bwd(res, do, scale, causal, block_q, block_k, dlse=dlse,
                      raw=raw)


_flash_attention_lse.defvjp(_flash_attention_lse_fwd,
                            _flash_attention_lse_bwd)


def flash_attention_with_lse(q, k, v, mask=None, causal=False, scale=None,
                             block_q=None, block_k=None):
    """flash_attention returning (o, lse[B, H, T, 1] fp32); both outputs
    are differentiable. Ragged shapes fall back to the jnp path."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_q, block_k, ragged = resolve_block_sizes(q, k, v, causal,
                                                   block_q, block_k)
    if ragged:
        return mha_reference(q, k, v, mask=mask, causal=causal,
                             scale=scale, return_lse=True)
    return _flash_attention_lse(q, k, v, mask, float(scale), bool(causal),
                                block_q, block_k,
                                not _use_custom_partitioning())


def flash_signature(b, h, t_q, t_kv, d, dtype, causal):
    """Autotune-table signature for a flash-attention shape. Exported so
    the sweep/promotion script (tests/perf/autotune_sweep.py) shares the
    exact format and cannot silently drop entries if it changes."""
    return "b{}_h{}_tq{}_tkv{}_d{}_{}_c{}".format(
        b, h, t_q, t_kv, d, jnp.dtype(dtype).name, int(bool(causal)))


def _autotuned_blocks(q, k, v, causal, default_q, default_k):
    """Per-shape tile selection via the autotuner (the reference sweeps
    cublas algos per shape at layer creation, gemm_test.h:27,141).

    Online sweeps need CONCRETE arrays to execute — when q is a tracer
    (flash_attention invoked inside an enclosing jit, the engine's normal
    path), only the bundled/user tables are consulted. Populate the table
    by calling flash_attention eagerly on the target shapes with
    DS_TPU_AUTOTUNE=1 (mirroring the reference, which also sweeps at layer
    creation, not per step)."""
    import jax.core

    from deepspeed_tpu.ops import autotuner

    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    sig = flash_signature(b, h, t_q, t_kv, d, q.dtype, causal)
    default = [min(default_q, t_q), min(default_k, t_kv)]
    traced = any(isinstance(x, jax.core.Tracer) for x in (q, k, v))
    if traced:
        cands = []  # table lookup only; sweeps cannot run during a trace
    else:
        cands = sorted({(min(bq, t_q), min(bk, t_kv))
                        for bq in (256, 512, 1024) for bk in (512, 1024)
                        if t_q % min(bq, t_q) == 0
                        and t_kv % min(bk, t_kv) == 0})
        cands = [list(c) for c in cands]

    def make_run(cand):
        bq, bk = cand
        reps = 10  # amortize dispatch/RTT: kernel time must dominate

        def fwd_bwd(x, y, z):
            eps = jnp.asarray(1e-7, x.dtype)  # nonzero: keeps grads live

            def once(carry, _):
                x_, y_, z_ = carry
                g = jax.grad(lambda a, b_, c: _flash_attention(
                    a, b_, c, None, 1.0 / d ** 0.5, bool(causal), bq, bk,
                    False).astype(jnp.float32).sum(),
                    argnums=(0, 1, 2))(x_, y_, z_)
                return (x_ + g[0] * eps, y_ + g[1] * eps,
                        z_ + g[2] * eps), None

            (x, y, z), _ = jax.lax.scan(once, (x, y, z), None, length=reps)
            return x

        jitted = jax.jit(fwd_bwd)

        def run():
            return jitted(q, k, v)
        return run

    choice = autotuner.autotune(
        "flash_attention", sig, cands, make_run, default=default)
    return int(choice[0]), int(choice[1])


def resolve_block_sizes(q, k, v, causal, block_q, block_k,
                        default_q=1024, default_k=1024):
    """(block_q, block_k, ragged) — the ONE block-selection policy shared
    by flash_attention, flash_attention_with_lse and ring attention:
    consult the per-shape autotuner when no explicit tiles were given (on
    TPU), default otherwise, clamp to the sequence extents, and flag
    shapes the tiled kernels cannot take (ragged => dense fallback)."""
    t_q, t_kv = q.shape[2], k.shape[2]
    if block_q is None and block_k is None and not _interpret():
        block_q, block_k = _autotuned_blocks(q, k, v, causal,
                                             default_q, default_k)
    bq = min(int(block_q or default_q), t_q)
    bk = min(int(block_k or default_k), t_kv)
    ragged = bool(t_q % bq or t_kv % bk)
    return bq, bk, ragged


def flash_attention(q, k, v, mask=None, causal=False, scale=None,
                    block_q=None, block_k=None):
    """Fused (flash) multi-head attention.

    Args:
      q, k, v: [B, H, T, D].
      mask: optional additive padding mask [B, T_kv] (0 keep / -1e9 drop),
        broadcast over heads and query rows — the reference's attention-mask
        convention (csrc/transformer/softmax_kernels.cu attn_softmax).
      causal: apply a causal (autoregressive) mask.
      scale: score scale; default 1/sqrt(D).
      block_q, block_k: VMEM tile sizes. Default (None) consults the
        per-shape autotuner table (ops/autotuner.py); its fallback 1024x1024
        was tuned on v5e (GPT-2 355M shapes, d=64).
    Returns: [B, H, T, D] in q.dtype.
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_q, block_k, ragged = resolve_block_sizes(q, k, v, causal,
                                                   block_q, block_k)
    if ragged:
        # Kernel reads fixed-size VMEM slices; ragged tails go to the
        # (differentiable) jnp path. Pad sequences to the block size to stay
        # on the fused kernel (SparseAttentionUtils.pad_to_block_size is the
        # helper, mirroring the reference's %16 padding,
        # ops/transformer/transformer.py:183-193).
        return mha_reference(q, k, v, mask=mask, causal=causal, scale=scale)
    return _flash_attention(q, k, v, mask, float(scale), bool(causal),
                            block_q, block_k,
                            not _use_custom_partitioning())
