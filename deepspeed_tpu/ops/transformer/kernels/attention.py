"""Fused multi-head attention — the TPU-native answer to the reference's
attention pipeline (reference csrc/transformer/ds_transformer_cuda.cpp:624:
qkv GEMM -> head split -> score GEMM -> launch_attn_softmax -> attn dropout
-> ctx GEMM -> head merge).

On GPU the reference fuses softmax/dropout between separate cuBLAS GEMMs,
materialising the [T, T] score matrix. On TPU the right fusion boundary is
different: one flash-style Pallas kernel keeps each score block in VMEM and
never writes the [T, T] matrix to HBM — O(T) memory instead of O(T^2), and
both GEMMs land on the MXU from the same kernel.

Forward: online-softmax accumulation over key/value blocks.
Backward: standard two-pass flash backward (one kernel produces dq looping
over kv blocks; one produces dk/dv looping over q blocks), using the saved
per-row logsumexp; wired up with jax.custom_vjp.

Off-TPU the kernels run in Pallas interpret mode, so the CPU test mesh
exercises the same code path (tests mirror reference
tests/unit/test_cuda_forward.py / test_cuda_backward.py grids).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Reference (pure jnp) implementation — ground truth for parity tests and
# fallback for shapes the kernel does not support.
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, mask=None, causal=False, scale=None):
    """q,k,v: [B, H, T, D]; mask: additive [B, T_kv] (broadcast over heads
    and query rows, the BERT padding-mask shape)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = s + mask[:, None, None, :].astype(jnp.float32)
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        cm = jnp.tril(jnp.ones((t_q, t_k), dtype=bool))
        s = jnp.where(cm[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, scale, causal, block_k, has_mask):
    if has_mask:
        q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        mask_ref = None

    q = q_ref[0, 0].astype(jnp.float32) * scale            # [bq, d]
    bq, d = q.shape
    t_kv = k_ref.shape[2]
    iq = pl.program_id(2)
    n_kv = pl.cdiv(t_kv, block_k)

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq, bk]
        if mask_ref is not None:
            s = s + mask_ref[0, pl.ds(j * block_k, block_k)][None, :]
        if causal:
            q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    # Under a causal mask, blocks past the diagonal contribute nothing.
    n_loop = jnp.minimum(n_kv, pl.cdiv((iq + 1) * bq, block_k)) if causal else n_kv
    acc, m, l = jax.lax.fori_loop(
        0, n_loop, body,
        (jnp.zeros((bq, d), jnp.float32),
         jnp.full((bq, 1), NEG_INF, jnp.float32),
         jnp.zeros((bq, 1), jnp.float32)))

    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)


def _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k):
    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_kv)
    grid = (b, h, pl.cdiv(t_q, block_q))

    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0)),
        pl.BlockSpec((1, 1, t_kv, d), lambda b_, h_, i: (b_, h_, 0, 0)),
        pl.BlockSpec((1, 1, t_kv, d), lambda b_, h_, i: (b_, h_, 0, 0)),
    ]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, t_kv), lambda b_, h_, i: (b_, 0)))
        args.append(mask.astype(jnp.float32))

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_k=block_k, has_mask=mask is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t_q, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------
# delta_i = rowsum(dO_i * O_i); then
#   dS = P * (dP - delta),  dq = dS K,  dk = dS^T q,  dv = P^T dO
# P is recomputed blockwise from q, k and the saved lse (never stored).

def _bwd_dq_kernel(*refs, scale, causal, block_k, has_mask):
    if has_mask:
        (q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
         dq_ref) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref = refs
        mask_ref = None

    q = q_ref[0, 0].astype(jnp.float32)                    # [bq, d]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]                                    # [bq, 1]
    delta = delta_ref[0, 0]
    bq, d = q.shape
    t_kv = k_ref.shape[2]
    iq = pl.program_id(2)
    n_kv = pl.cdiv(t_kv, block_k)

    def body(j, dq):
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if mask_ref is not None:
            s = s + mask_ref[0, pl.ds(j * block_k, block_k)][None, :]
        if causal:
            q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [bq, bk]
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    n_loop = jnp.minimum(n_kv, pl.cdiv((iq + 1) * bq, block_k)) if causal else n_kv
    dq = jax.lax.fori_loop(0, n_loop, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, block_q, has_mask):
    if has_mask:
        (q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
         dv_ref) = refs
        mask_ref = None

    k_blk = k_ref[0, 0].astype(jnp.float32)                # [bk, d]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    bk, d = k_blk.shape
    t_q = q_ref.shape[2]
    jk = pl.program_id(2)
    n_q = pl.cdiv(t_q, block_q)
    if mask_ref is not None:
        mask_blk = mask_ref[0][None, :]                    # [1, bk]
    else:
        mask_blk = None

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * block_q, block_q)].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(i * block_q, block_q)].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if mask_blk is not None:
            s = s + mask_blk
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            k_pos = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [bq, bk]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # Query blocks strictly above this kv block's diagonal are masked out.
        start = (jk * bk) // block_q
    else:
        start = 0
    dk, dv = jax.lax.fori_loop(
        start, n_q, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, scale, causal, block_q, block_k):
    q, k, v, mask, o, lse = res
    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_kv)
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0))
    q_full = pl.BlockSpec((1, 1, t_q, d), lambda b_, h_, j: (b_, h_, 0, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, j: (b_, h_, j, 0))
    kv_full = pl.BlockSpec((1, 1, t_kv, d), lambda b_, h_, i: (b_, h_, 0, 0))
    row_blk = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i: (b_, h_, i, 0))
    row_full = pl.BlockSpec((1, 1, t_q, 1), lambda b_, h_, j: (b_, h_, 0, 0))

    # dq: grid over q blocks.
    in_specs = [q_spec, kv_full, kv_full]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, t_kv), lambda b_, h_, i: (b_, 0)))
        args.append(mask.astype(jnp.float32))
    in_specs += [q_spec, row_blk, row_blk]
    args += [do, lse, delta]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, has_mask=mask is not None),
        grid=(b, h, pl.cdiv(t_q, block_q)),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(*args)

    # dk/dv: grid over kv blocks.
    in_specs = [q_full, kv_spec, kv_spec]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, block_k), lambda b_, h_, j: (b_, j)))
        args.append(mask.astype(jnp.float32))
    in_specs += [q_full, row_full, row_full]
    args += [do, lse, delta]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, has_mask=mask is not None),
        grid=(b, h, pl.cdiv(t_kv, block_k)),
        in_specs=in_specs,
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        interpret=_interpret(),
    )(*args)

    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dmask


# ---------------------------------------------------------------------------
# Public entry point
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention(q, k, v, mask, scale, causal, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k)
    return o


def _flash_attention_fwd(q, k, v, mask, scale, causal, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k)
    return o, (q, k, v, mask, o, lse)


def _flash_attention_bwd(scale, causal, block_q, block_k, res, g):
    return _flash_bwd(res, g, scale, causal, block_q, block_k)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(q, k, v, mask=None, causal=False, scale=None,
                    block_q=128, block_k=128):
    """Fused (flash) multi-head attention.

    Args:
      q, k, v: [B, H, T, D].
      mask: optional additive padding mask [B, T_kv] (0 keep / -1e9 drop),
        broadcast over heads and query rows — the reference's attention-mask
        convention (csrc/transformer/softmax_kernels.cu attn_softmax).
      causal: apply a causal (autoregressive) mask.
      scale: score scale; default 1/sqrt(D).
    Returns: [B, H, T, D] in q.dtype.
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    t_q, t_kv = q.shape[2], k.shape[2]
    block_q = min(int(block_q), t_q)
    block_k = min(int(block_k), t_kv)
    if t_q % block_q or t_kv % block_k:
        # Kernel reads fixed-size VMEM slices; ragged tails go to the
        # (differentiable) jnp path. Pad sequences to the block size to stay
        # on the fused kernel (SparseAttentionUtils.pad_to_block_size is the
        # helper, mirroring the reference's %16 padding,
        # ops/transformer/transformer.py:183-193).
        return mha_reference(q, k, v, mask=mask, causal=causal, scale=scale)
    return _flash_attention(q, k, v, mask, float(scale), bool(causal),
                            block_q, block_k)
