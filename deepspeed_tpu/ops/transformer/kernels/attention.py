"""Fused multi-head attention — the TPU-native answer to the reference's
attention pipeline (reference csrc/transformer/ds_transformer_cuda.cpp:624:
qkv GEMM -> head split -> score GEMM -> launch_attn_softmax -> attn dropout
-> ctx GEMM -> head merge).

On GPU the reference fuses softmax/dropout between separate cuBLAS GEMMs,
materialising the [T, T] score matrix. On TPU the right fusion boundary is
different: one flash-style Pallas kernel keeps each score block in VMEM and
never writes the [T, T] matrix to HBM — O(T) memory instead of O(T^2), and
both GEMMs land on the MXU from the same kernel.

Kernel structure (the part that makes it fast):
- the key/value block loop is a GRID dimension, not a fori_loop over a
  whole-[T, d] VMEM residency: Pallas double-buffers the per-block DMAs
  against compute, so HBM reads overlap the MXU;
- matmul inputs stay in the model dtype (bf16) with fp32 MXU accumulation
  (preferred_element_type); softmax statistics and the output accumulator
  live in fp32 VMEM scratch across grid steps;
- causal masking skips fully-masked key blocks: their index map clamps to
  the last useful block (no new DMA is issued for a repeated index) and
  @pl.when skips the compute.

Forward: online-softmax accumulation over key/value blocks.
Backward: standard two-pass flash backward (one kernel produces dq looping
over kv blocks; one produces dk/dv looping over q blocks), using the saved
per-row logsumexp; wired up with jax.custom_vjp.

Off-TPU the kernels run in Pallas interpret mode, so the CPU test mesh
exercises the same code path (tests mirror reference
tests/unit/test_cuda_forward.py / test_cuda_backward.py grids).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
# Lane width for the fp32 softmax-statistic scratch rows: Mosaic pads
# second-minor×minor tiles to (8, 128), so statistics are kept broadcast
# across a full 128-lane row instead of a width-1 column.
_STATS_LANES = 128


def _interpret():
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Reference (pure jnp) implementation — ground truth for parity tests and
# fallback for shapes the kernel does not support.
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, mask=None, causal=False, scale=None):
    """q,k,v: [B, H, T, D]; mask: additive [B, T_kv] (broadcast over heads
    and query rows, the BERT padding-mask shape)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = s + mask[:, None, None, :].astype(jnp.float32)
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        cm = jnp.tril(jnp.ones((t_q, t_k), dtype=bool))
        s = jnp.where(cm[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _last_kv_block(iq, block_q, block_k):
    """Index of the last key block a causal query block iq attends to."""
    return ((iq + 1) * block_q - 1) // block_k


def _first_q_block(jk, block_q, block_k):
    """Index of the first query block that attends to causal key block jk."""
    return (jk * block_k) // block_q


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, scale, causal, block_q, block_k, has_mask):
    if has_mask:
        q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, acc, m_s, l_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s = refs
        mask_ref = None

    iq = pl.program_id(2)
    j = pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    if causal:
        active = j <= _last_kv_block(iq, block_q, block_k)
    else:
        active = j < n_kv

    @pl.when(active)
    def _compute():
        q = q_ref[0, 0]                                    # [bq, d] model dtype
        k_blk = k_ref[0, 0]                                # [bk, d]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if mask_ref is not None:
            s = s + mask_ref[0][None, :]
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_s[:, 0:1]                               # [bq, 1]
        l_prev = l_s[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                             # [bq, bk] fp32
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)
        # Second MXU matmul in the model dtype with fp32 accumulation.
        pv = jax.lax.dot_general(p.astype(v_blk.dtype), v_blk,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc[...] = acc[...] * alpha + pv

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = jnp.maximum(l_s[:, 0:1], 1e-30)
        o_ref[0, 0] = (acc[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_s[:, 0:1] + jnp.log(l)


def _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k):
    from jax.experimental.pallas import tpu as pltpu

    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_kv)
    n_kv = pl.cdiv(t_kv, block_k)
    grid = (b, h, pl.cdiv(t_q, block_q), n_kv)

    if causal:
        def kv_index(b_, h_, i, j):
            # Clamp past-diagonal blocks to the last useful one: a repeated
            # block index issues no new DMA, and @pl.when skips the compute.
            return (b_, h_, jnp.minimum(j, _last_kv_block(i, block_q, block_k)), 0)
    else:
        def kv_index(b_, h_, i, j):
            return (b_, h_, j, 0)

    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        pl.BlockSpec((1, 1, block_k, d), kv_index),
        pl.BlockSpec((1, 1, block_k, d), kv_index),
    ]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(
            pl.BlockSpec((1, block_k), lambda b_, h_, i, j: (b_, kv_index(b_, h_, i, j)[2])))
        args.append(mask.astype(jnp.float32))

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          has_mask=mask is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------
# delta_i = rowsum(dO_i * O_i); then
#   dS = P * (dP - delta),  dq = dS K,  dk = dS^T q,  dv = P^T dO
# P is recomputed blockwise from q, k and the saved lse (never stored).

def _bwd_dq_kernel(*refs, scale, causal, block_q, block_k, has_mask):
    if has_mask:
        (q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
         dq_acc) = refs
        mask_ref = None

    iq = pl.program_id(2)
    j = pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if causal:
        active = j <= _last_kv_block(iq, block_q, block_k)
    else:
        active = j < n_kv

    @pl.when(active)
    def _compute():
        q = q_ref[0, 0]                                    # [bq, d]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]                                # [bq, 1]
        delta = delta_ref[0, 0]
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if mask_ref is not None:
            s = s + mask_ref[0][None, :]
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [bq, bk] fp32
        dp = jax.lax.dot_general(do.astype(v_blk.dtype), v_blk,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k_blk.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == n_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, block_q, block_k, has_mask):
    if has_mask:
        (q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
         dv_ref, dk_acc, dv_acc) = refs
        mask_ref = None

    jk = pl.program_id(2)
    i = pl.program_id(3)
    n_q = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if causal:
        active = i >= _first_q_block(jk, block_q, block_k)
    else:
        active = i < n_q

    @pl.when(active)
    def _compute():
        k_blk = k_ref[0, 0]                                # [bk, d]
        v_blk = v_ref[0, 0]
        q = q_ref[0, 0]                                    # [bq, d]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                                # [bq, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if mask_ref is not None:
            s = s + mask_ref[0][None, :]
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [bq, bk] fp32
        p_cast = p.astype(do.dtype)
        dv_acc[...] += jax.lax.dot_general(
            p_cast, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do.astype(v_blk.dtype), v_blk,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == n_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(res, g, scale, causal, block_q, block_k):
    from jax.experimental.pallas import tpu as pltpu

    q, k, v, mask, o, lse = res
    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_kv)
    n_q = pl.cdiv(t_q, block_q)
    n_kv = pl.cdiv(t_kv, block_k)
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    # dq: grid over (q block, kv block), kv innermost and pipelined.
    if causal:
        def kv_index(b_, h_, i, j):
            return (b_, h_, jnp.minimum(j, _last_kv_block(i, block_q, block_k)), 0)
    else:
        def kv_index(b_, h_, i, j):
            return (b_, h_, j, 0)
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d), kv_index)
    row_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0))

    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(
            pl.BlockSpec((1, block_k), lambda b_, h_, i, j: (b_, kv_index(b_, h_, i, j)[2])))
        args.append(mask.astype(jnp.float32))
    in_specs += [q_spec, row_spec, row_spec]
    args += [do, lse, delta]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          has_mask=mask is not None),
        grid=(b, h, n_q, n_kv),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*args)

    # dk/dv: grid over (kv block, q block), q innermost and pipelined.
    if causal:
        def q_index(b_, h_, jk, i):
            return (b_, h_, jnp.maximum(i, _first_q_block(jk, block_q, block_k)), 0)
    else:
        def q_index(b_, h_, jk, i):
            return (b_, h_, i, 0)
    q_spec2 = pl.BlockSpec((1, 1, block_q, d), q_index)
    kv_spec2 = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, jk, i: (b_, h_, jk, 0))
    row_spec2 = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b_, h_, jk, i: (b_, h_, q_index(b_, h_, jk, i)[2], 0))

    in_specs = [q_spec2, kv_spec2, kv_spec2]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, block_k), lambda b_, h_, jk, i: (b_, jk)))
        args.append(mask.astype(jnp.float32))
    in_specs += [q_spec2, row_spec2, row_spec2]
    args += [do, lse, delta]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          has_mask=mask is not None),
        grid=(b, h, n_kv, n_q),
        in_specs=in_specs,
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_interpret(),
    )(*args)

    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dmask


# ---------------------------------------------------------------------------
# Public entry point
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention(q, k, v, mask, scale, causal, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k)
    return o


def _flash_attention_fwd(q, k, v, mask, scale, causal, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k)
    return o, (q, k, v, mask, o, lse)


def _flash_attention_bwd(scale, causal, block_q, block_k, res, g):
    return _flash_bwd(res, g, scale, causal, block_q, block_k)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def _autotuned_blocks(q, k, v, causal, default_q, default_k):
    """Per-shape tile selection via the autotuner (the reference sweeps
    cublas algos per shape at layer creation, gemm_test.h:27,141).

    Online sweeps need CONCRETE arrays to execute — when q is a tracer
    (flash_attention invoked inside an enclosing jit, the engine's normal
    path), only the bundled/user tables are consulted. Populate the table
    by calling flash_attention eagerly on the target shapes with
    DS_TPU_AUTOTUNE=1 (mirroring the reference, which also sweeps at layer
    creation, not per step)."""
    import jax.core

    from deepspeed_tpu.ops import autotuner

    b, h, t_q, d = q.shape
    t_kv = k.shape[2]
    sig = "b{}_h{}_tq{}_tkv{}_d{}_{}_c{}".format(
        b, h, t_q, t_kv, d, q.dtype.name, int(bool(causal)))
    default = [min(default_q, t_q), min(default_k, t_kv)]
    traced = any(isinstance(x, jax.core.Tracer) for x in (q, k, v))
    if traced:
        cands = []  # table lookup only; sweeps cannot run during a trace
    else:
        cands = sorted({(min(bq, t_q), min(bk, t_kv))
                        for bq in (256, 512, 1024) for bk in (512, 1024)
                        if t_q % min(bq, t_q) == 0
                        and t_kv % min(bk, t_kv) == 0})
        cands = [list(c) for c in cands]

    def make_run(cand):
        bq, bk = cand
        reps = 10  # amortize dispatch/RTT: kernel time must dominate

        def fwd_bwd(x, y, z):
            eps = jnp.asarray(1e-7, x.dtype)  # nonzero: keeps grads live

            def once(carry, _):
                x_, y_, z_ = carry
                g = jax.grad(lambda a, b_, c: _flash_attention(
                    a, b_, c, None, 1.0 / d ** 0.5, bool(causal), bq, bk
                ).astype(jnp.float32).sum(), argnums=(0, 1, 2))(x_, y_, z_)
                return (x_ + g[0] * eps, y_ + g[1] * eps,
                        z_ + g[2] * eps), None

            (x, y, z), _ = jax.lax.scan(once, (x, y, z), None, length=reps)
            return x

        jitted = jax.jit(fwd_bwd)

        def run():
            return jitted(q, k, v)
        return run

    choice = autotuner.autotune(
        "flash_attention", sig, cands, make_run, default=default)
    return int(choice[0]), int(choice[1])


def flash_attention(q, k, v, mask=None, causal=False, scale=None,
                    block_q=None, block_k=None):
    """Fused (flash) multi-head attention.

    Args:
      q, k, v: [B, H, T, D].
      mask: optional additive padding mask [B, T_kv] (0 keep / -1e9 drop),
        broadcast over heads and query rows — the reference's attention-mask
        convention (csrc/transformer/softmax_kernels.cu attn_softmax).
      causal: apply a causal (autoregressive) mask.
      scale: score scale; default 1/sqrt(D).
      block_q, block_k: VMEM tile sizes. Default (None) consults the
        per-shape autotuner table (ops/autotuner.py); its fallback 1024x1024
        was tuned on v5e (GPT-2 355M shapes, d=64): 2.1x over dense XLA
        attention at T=1024 fwd+bwd, 3.0x at T=2048.
    Returns: [B, H, T, D] in q.dtype.
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    t_q, t_kv = q.shape[2], k.shape[2]
    if block_q is None and block_k is None and not _interpret():
        block_q, block_k = _autotuned_blocks(q, k, v, causal, 1024, 1024)
    else:
        block_q = block_q if block_q is not None else 1024
        block_k = block_k if block_k is not None else 1024
    block_q = min(int(block_q), t_q)
    block_k = min(int(block_k), t_kv)
    if t_q % block_q or t_kv % block_k:
        # Kernel reads fixed-size VMEM slices; ragged tails go to the
        # (differentiable) jnp path. Pad sequences to the block size to stay
        # on the fused kernel (SparseAttentionUtils.pad_to_block_size is the
        # helper, mirroring the reference's %16 padding,
        # ops/transformer/transformer.py:183-193).
        return mha_reference(q, k, v, mask=mask, causal=causal, scale=scale)
    return _flash_attention(q, k, v, mask, float(scale), bool(causal),
                            block_q, block_k)
