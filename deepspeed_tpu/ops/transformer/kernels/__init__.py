"""Pallas TPU kernels — the native-op tier (reference csrc/transformer)."""

from deepspeed_tpu.ops.transformer.kernels.attention import (  # noqa: F401
    flash_attention, mha_reference)
from deepspeed_tpu.ops.transformer.kernels.decode_attention import (  # noqa: F401,E501
    decode_attention_reference, flash_decode_attention)
from deepspeed_tpu.ops.transformer.kernels.dropout import (  # noqa: F401
    dropout, fused_bias_dropout_residual)
from deepspeed_tpu.ops.transformer.kernels.gelu import (  # noqa: F401
    bias_gelu_reference, fused_bias_gelu)
from deepspeed_tpu.ops.transformer.kernels.layer_norm import (  # noqa: F401
    fused_bias_residual_layer_norm, fused_layer_norm, layer_norm_reference)
from deepspeed_tpu.ops.transformer.kernels.softmax import (  # noqa: F401
    attn_softmax, attn_softmax_reference)
