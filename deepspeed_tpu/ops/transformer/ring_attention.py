"""Ring attention — sequence-parallel flash attention over a mesh axis.

Long-context support beyond the reference: DeepSpeed v0.3.10's only
long-sequence lever is block-sparse attention (verified in SURVEY §0/§5.7 —
no sequence/context parallelism anywhere in that tree). On TPU, sequences
that exceed one chip's HBM shard naturally over the ICI ring: each device
holds a [T/N] slice of q/k/v, computes flash attention against its local
key/value block, then rotates the k/v blocks around the ring with
``jax.lax.ppermute`` — after N-1 rotations every query block has attended
every key block, with O(T/N) activation memory per chip and communication
fully overlappable with the per-block flash kernels.

Design notes:
- The per-block compute is the SAME Pallas flash kernel pair as
  single-chip attention (`kernels/attention.py`: `_flash_fwd_pallas` /
  `_flash_bwd_pallas`); forward partials merge by logsumexp algebra:
      m = max(lse_a, lse_b);  w = exp(lse - m)
      o = (o_a w_a + o_b w_b) / (w_a + w_b);  lse = m + log(w_a + w_b)
  which is exactly the flash online-softmax update at ring granularity.
  Shard lengths the tiled kernels cannot take (ragged vs the tile size)
  use a dense jnp per-block compute instead.
- Causality is decided at BLOCK level from the ring step: source block j
  attends destination block i fully when j < i, causally (diagonal) when
  j == i, and not at all when j > i — the skipped blocks never run a
  kernel (lax.cond on the uniform ring counter) and contribute a NEG_INF
  lse, making the merge a no-op.
- An additive key padding mask ([B, T] over GLOBAL key positions, sharded
  like k/v) rotates around the ring alongside its k/v block.
- The backward is a hand-written custom VJP (`_ring_bwd_scan`): it
  re-rotates k/v and recomputes per-block probabilities from the saved
  GLOBAL logsumexp and delta = rowsum(dO*O) (the flash identity
  ds = p*(dp - delta) holds per block with global statistics); dk/dv
  accumulate in buffers that travel with their block and arrive home
  after the n-th rotation. O(T/N) memory per device in both directions —
  autodiff-through-scan would checkpoint every rotated k/v block.
- Call inside ``shard_map`` with the sequence dim sharded over
  ``axis_name`` (helper ``sequence_parallel_attention`` wraps this for a
  mesh). The batch dim may additionally be sharded over 'data' as usual.
"""

import functools

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils import jax_compat

from deepspeed_tpu.ops.transformer.kernels.attention import (
    NEG_INF, _flash_bwd_pallas, _flash_fwd_pallas, _mxu_precision,
    flash_attention_with_lse, mha_reference, resolve_block_sizes)


def _merge(o_a, lse_a, o_b, lse_b):
    """Combine two partial attention results over the same queries.
    o: [B, H, T, D] fp32; lse: [B, H, T, 1] fp32. Skipped blocks carry
    lse = NEG_INF (-1e30, finite): after subtracting the max their weight
    underflows to exactly 0, so no special-casing is needed — the max side
    always contributes weight exp(0) = 1 and the denominator is >= 1."""
    m = jnp.maximum(lse_a, lse_b)
    w_a = jnp.exp(lse_a - m)
    w_b = jnp.exp(lse_b - m)
    denom = w_a + w_b
    o = (o_a * w_a + o_b * w_b) / denom
    return o, m + jnp.log(denom)


def _dense_block_fwd(q, k, v, mask, scale, causal):
    """Dense jnp per-block (o, lse) for shard lengths the tiled kernels
    cannot take — one shared dense implementation (mha_reference)."""
    return mha_reference(q, k, v, mask=mask, causal=causal, scale=scale,
                         return_lse=True)


def _dense_block_bwd(q, k, v, mask, delta, lse, do, scale, causal):
    """Dense jnp per-block flash backward with GLOBAL row statistics:
    p = exp(s - lse), ds = p * (dp - delta).

    The recomputed s must round the same way the forward (mha_reference)
    did, or p no longer matches the saved lse — so the einsums share the
    forward's dtype-dependent precision rule (fp32 -> HIGHEST on the MXU,
    bf16/fp16 -> DEFAULT, where fwd/bwd rounding cancels)."""
    prec = _mxu_precision(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32), precision=prec) * scale
    if mask is not None:
        s = s + mask[:, None, None, :].astype(jnp.float32)
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        cm = jnp.tril(jnp.ones((t_q, t_k), dtype=bool))
        s = jnp.where(cm[None, None], s, NEG_INF)
    # s <= lse mathematically; the clamp guards fully-masked rows where
    # fp32 lse (~-1e9, ulp 64) loses the log-sum bits — exp of a spurious
    # +64 would poison the whole step with inf grads.
    p = jnp.exp(jnp.minimum(s - lse, 0.0))
    do32 = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, do32, precision=prec)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do32, v.astype(jnp.float32),
                    precision=prec)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32),
                    precision=prec)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32),
                    precision=prec)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _block_fwd(q, k, v, mask, scale, causal, bq, bk, dense):
    if dense:
        return _dense_block_fwd(q, k, v, mask, scale, causal)
    return _flash_fwd_pallas(q, k, v, mask, scale, causal, bq, bk)


def _block_bwd(q, k, v, mask, delta, lse, do, scale, causal, bq, bk,
               dense):
    if dense:
        return _dense_block_bwd(q, k, v, mask, delta, lse, do, scale,
                                causal)
    return _flash_bwd_pallas(q, k, v, mask, delta, lse, do, scale, causal,
                             bq, bk)


def _ring_fwd_scan(q, k, v, mask, axis_name, causal, scale, bq, bk, dense):
    """(o fp32, lse) after the full ring. mask: fp32 [B, T_local] or None."""
    n = jax_compat.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    o0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    lse0 = jnp.full((b, h, t_local, 1), NEG_INF, jnp.float32)
    has_mask = mask is not None
    # The mask occupies a scan-carry slot (rotating with its k/v block)
    # only when present - a dead zeros-mask would cost one extra ppermute
    # per ring step per layer.
    mask_carry = (mask,) if has_mask else ()
    # Ring neighbour: receive from the previous rank, send to the next, so
    # at step s the local device holds k/v block (my - s) mod n.
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        o, lse, k_blk, v_blk = carry[:4]
        cur_mask = carry[4] if has_mask else None
        src = (my - s) % n

        def full_block():
            oc, lc = _block_fwd(q, k_blk, v_blk, cur_mask, scale, False,
                                bq, bk, dense)
            return oc.astype(jnp.float32), lc

        if causal:
            def diag_block():
                od, ld = _block_fwd(q, k_blk, v_blk, cur_mask, scale,
                                    True, bq, bk, dense)
                return od.astype(jnp.float32), ld

            def skipped_block():
                return jnp.zeros_like(o0), jnp.full_like(lse0, NEG_INF)

            # Block-level causality by ring step: src > my contributes
            # nothing (and its kernels never run - cond, not where).
            o_p, lse_p = jax.lax.cond(
                src > my, skipped_block,
                lambda: jax.lax.cond(src == my, diag_block, full_block))
        else:
            o_p, lse_p = full_block()
        o, lse = _merge(o, lse, o_p, lse_p)

        # Rotate k/v (+mask) for the next step. The final step's rotation
        # would be discarded - skip it (the predicate is the scan counter,
        # identical on every device, so the collective stays globally
        # consistent).
        def rotate(kvm):
            return tuple(jax.lax.ppermute(x, axis_name, perm) for x in kvm)

        rolling = (k_blk, v_blk) + ((cur_mask,) if has_mask else ())
        rolling = jax.lax.cond(s < n - 1, rotate, lambda kvm: kvm, rolling)
        return (o, lse) + rolling, None

    (o, lse, *_), _ = jax.lax.scan(step, (o0, lse0, k, v) + mask_carry,
                                   jnp.arange(n))
    return o, lse


def _ring_bwd_scan(q, k, v, mask, o, lse, do, axis_name, causal, scale,
                   bq, bk, dense):
    """Recompute-and-re-rotate ring backward: O(T/N) memory per device.

    The per-block backward is the SAME two-pass flash backward as
    single-chip attention, fed the GLOBAL row statistics (lse and
    delta = rowsum(dO*O)) - the flash identity ds = p*(dp - delta) holds
    per block with global delta. dq accumulates locally; dk/dv accumulate
    in buffers that TRAVEL WITH their k/v block and arrive home after the
    n-th rotation.
    """
    n = jax_compat.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    has_mask = mask is not None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        dq_acc, dk_rot, dv_rot, k_blk, v_blk = carry[:5]
        cur_mask = carry[5] if has_mask else None
        src = (my - s) % n

        def block(causal_mode):
            return _block_bwd(q, k_blk, v_blk, cur_mask, delta, lse, do,
                              scale, causal_mode, bq, bk, dense)

        def full_block():
            return block(False)

        if causal:
            def diag_block():
                return block(True)

            def skipped_block():
                return (jnp.zeros(q.shape, q.dtype),
                        jnp.zeros(k.shape, k.dtype),
                        jnp.zeros(v.shape, v.dtype))

            dq_p, dk_p, dv_p = jax.lax.cond(
                src > my, skipped_block,
                lambda: jax.lax.cond(src == my, diag_block, full_block))
        else:
            dq_p, dk_p, dv_p = full_block()

        dq_acc = dq_acc + dq_p.astype(jnp.float32)
        dk_rot = dk_rot + dk_p.astype(jnp.float32)
        dv_rot = dv_rot + dv_p.astype(jnp.float32)
        # The grad buffers rotate on EVERY step (n rotations total bring
        # block my's gradients home); k/v/mask skip the final dead hop.
        dk_rot = jax.lax.ppermute(dk_rot, axis_name, perm)
        dv_rot = jax.lax.ppermute(dv_rot, axis_name, perm)

        def rotate(kvm):
            return tuple(jax.lax.ppermute(x, axis_name, perm) for x in kvm)

        rolling = (k_blk, v_blk) + ((cur_mask,) if has_mask else ())
        rolling = jax.lax.cond(s < n - 1, rotate, lambda kvm: kvm, rolling)
        return (dq_acc, dk_rot, dv_rot) + rolling, None

    carry0 = (dq0, dk0, dv0, k, v) + ((mask,) if has_mask else ())
    (dq, dk, dv, *_), _ = jax.lax.scan(step, carry0, jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring(q, k, v, mask, axis_name, causal, scale, bq, bk, dense):
    o, _ = _ring_fwd_scan(q, k, v, mask, axis_name, causal, scale, bq, bk,
                          dense)
    return o.astype(q.dtype)


def _ring_fwd(q, k, v, mask, axis_name, causal, scale, bq, bk, dense):
    o, lse = _ring_fwd_scan(q, k, v, mask, axis_name, causal, scale,
                            bq, bk, dense)
    o = o.astype(q.dtype)
    return o, (q, k, v, mask, o, lse)


def _ring_bwd(axis_name, causal, scale, bq, bk, dense, res, do):
    q, k, v, mask, o, lse = res
    dq, dk, dv = _ring_bwd_scan(q, k, v, mask, o, lse, do, axis_name,
                                causal, scale, bq, bk, dense)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dmask


_ring.defvjp(_ring_fwd, _ring_bwd)


def ring_flash_attention(q, k, v, axis_name, causal=False, mask=None,
                         scale=None, block_q=None, block_k=None):
    """Flash attention over sequence shards on a ring. SPMD-collective:
    must run inside shard_map (or pmap) with ``axis_name`` bound, with
    q/k/v sequence dims sharded over that axis.

    Memory is O(T/N) per device in BOTH directions: the custom backward
    re-rotates k/v and recomputes per-block probabilities from the saved
    global logsumexp (the flash recompute trick at ring granularity) -
    autodiff-through-scan would instead checkpoint every rotated k/v
    block, i.e. the full O(T) key/value set.

    Args:
      q, k, v: [B, H, T_local, D] - the local sequence shard.
      axis_name: mesh axis the sequence is sharded over.
      causal: causal masking in GLOBAL sequence positions (shards are
        assumed laid out in axis-index order).
      mask: optional additive key padding mask shard [B, T_local]
        (0 keep / -1e9 drop), covering this shard's KEY positions; it
        rotates with the k/v blocks (non-differentiable, like the flash
        kernel's mask).
      scale: score scale; default 1/sqrt(D).
      block_q, block_k: Pallas tile sizes for the local kernel. Default
        (None) consults the per-shape autotuner table for the LOCAL
        block shapes. Shard lengths not divisible by the tiles use a
        dense jnp per-block compute (any length works; O(t_local^2)
        score memory per block pair).
    Returns: [B, H, T_local, D] in q.dtype.
    """
    n = jax_compat.axis_size(axis_name)
    d = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)

    if n == 1:
        return flash_attention_with_lse(
            q, k, v, mask=mask, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k)[0]

    # Tile lookup keys the NON-causal autotuner entry: in a causal ring
    # n-1 of the n block kernels are the full (non-causal) variant — the
    # diagonal causal call is the minority. The semantic causal flag is
    # passed to the kernels unchanged.
    bq, bk, dense = resolve_block_sizes(q, k, v, False, block_q, block_k)
    mask_f = None if mask is None else mask.astype(jnp.float32)
    return _ring(q, k, v, mask_f, axis_name, bool(causal), scale, bq, bk,
                 dense)


def sequence_parallel_attention(mesh, q, k, v, axis_name="data",
                                causal=False, mask=None, scale=None,
                                block_q=None, block_k=None):
    """shard_map wrapper: q/k/v are GLOBAL [B, H, T, D] arrays (or host
    numpy); the sequence dim is sharded over ``axis_name`` and attention
    runs as a ring. ``mask`` is the GLOBAL [B, T] additive key padding
    mask. Batch/head dims stay replicated here — compose with
    data-parallel batch sharding by calling ring_flash_attention directly
    inside your own shard_map."""
    from deepspeed_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    ring = functools.partial(ring_flash_attention, axis_name=axis_name,
                             causal=causal, scale=scale, block_q=block_q,
                             block_k=block_k)
    if mask is None:
        fn = shard_map(ring, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
        return fn(q, k, v)
    fn = shard_map(lambda q, k, v, m: ring(q, k, v, mask=m),
                   mesh=mesh,
                   in_specs=(spec, spec, spec, P(None, axis_name)),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v, mask)


def get_sp_attention(mode):
    """Resolve a sequence_parallel_mode string to its attention
    implementation; unknown modes raise instead of silently running a
    different collective pattern."""
    impls = {"ring": ring_flash_attention, "ulysses": ulysses_attention}
    try:
        return impls[mode]
    except KeyError:
        raise ValueError(
            "unknown sequence_parallel_mode {!r}; expected one of {}"
            .format(mode, sorted(impls))) from None


def ulysses_attention(q, k, v, axis_name, causal=False, mask=None,
                      scale=None, block_q=None, block_k=None):
    """All-to-all (DeepSpeed-Ulysses-style) sequence-parallel attention —
    the other classic context-parallel decomposition, complementing the
    ring: two ``jax.lax.all_to_all`` exchanges swap the TOKEN sharding for
    a HEAD sharding, each device runs ordinary full-sequence flash
    attention for its H/N head subset, and the reverse exchange restores
    token sharding. Versus the ring: 2 all-to-alls instead of N-1
    ppermutes (better for small N / fast ICI), but requires num_heads
    divisible by the axis size and materializes the full sequence per
    device (memory O(T·H/N) instead of O(T/N·H)).

    SPMD-collective: call inside shard_map with ``axis_name`` bound.

    Args:
      q, k, v: [B, H, T_local, D] — the local sequence shard.
      axis_name: mesh axis the sequence is sharded over.
      causal: causal masking (global positions).
      mask: optional additive key padding mask shard [B, T_local]
        (gathered to the full [B, T] for the local attention).
      scale, block_q, block_k: forwarded to flash_attention.
    Returns: [B, H, T_local, D] in q.dtype.
    """
    from deepspeed_tpu.ops.transformer.kernels.attention import (
        flash_attention)

    n = jax_compat.axis_size(axis_name)
    if n == 1:
        return flash_attention(q, k, v, mask=mask, causal=causal,
                               scale=scale, block_q=block_q,
                               block_k=block_k)
    h = q.shape[1]
    if h % n:
        raise ValueError(
            "ulysses_attention requires num_heads ({}) divisible by the "
            "'{}' axis size ({}); use ring attention for more shards "
            "than heads".format(h, axis_name, n))

    def to_tokens(x):    # [B, H/n, T, D] -> [B, H, T/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    full_mask = None
    if mask is not None:
        full_mask = jax.lax.all_gather(mask, axis_name, axis=1, tiled=True)
    # One exchange for all three tensors (q/k/v stacked): the documented
    # "two all_to_alls per layer" — one in, one out.
    qkv = jax.lax.all_to_all(jnp.stack([q, k, v]), axis_name,
                             split_axis=2, concat_axis=3, tiled=True)
    o = flash_attention(qkv[0], qkv[1], qkv[2],
                        mask=full_mask, causal=causal, scale=scale,
                        block_q=block_q, block_k=block_k)
    return to_tokens(o)
