"""Ring attention — sequence-parallel flash attention over a mesh axis.

Long-context support beyond the reference: DeepSpeed v0.3.10's only
long-sequence lever is block-sparse attention (verified in SURVEY §0/§5.7 —
no sequence/context parallelism anywhere in that tree). On TPU, sequences
that exceed one chip's HBM shard naturally over the ICI ring: each device
holds a [T/N] slice of q/k/v, computes flash attention against its local
key/value block, then rotates the k/v blocks around the ring with
``jax.lax.ppermute`` — after N-1 rotations every query block has attended
every key block, with O(T/N) activation memory per chip and communication
fully overlappable with the per-block flash kernels.

Design notes:
- The per-block compute is the SAME Pallas flash kernel as single-chip
  attention (`kernels/attention.py`), invoked with return_lse=True; partial
  results merge by logsumexp algebra:
      m = max(lse_a, lse_b);  w = exp(lse - m)
      o = (o_a w_a + o_b w_b) / (w_a + w_b);  lse = m + log(w_a + w_b)
  which is exactly the flash online-softmax update at ring granularity.
- Causality is decided at BLOCK level from the ring step: source block j
  attends destination block i fully when j < i, causally (diagonal) when
  j == i, and not at all when j > i — the skipped blocks never run a
  kernel (lax.cond on the uniform ring counter) and contribute a NEG_INF
  lse, making the merge a no-op.
- An additive key padding mask ([B, T] over GLOBAL key positions, sharded
  like k/v) rotates around the ring alongside its k/v block.
- The backward pass needs no hand-written collective: the merge is
  differentiable jnp, the per-block kernel has its custom_vjp, and
  ppermute's transpose is the reverse permute — `jax.lax.scan` over ring
  steps gives autodiff the full recomputation structure.
- Call inside ``shard_map`` with the sequence dim sharded over
  ``axis_name`` (helper ``sequence_parallel_attention`` wraps this for a
  mesh). The batch dim may additionally be sharded over 'data' as usual.
"""

import functools

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.kernels.attention import (
    NEG_INF, flash_attention_with_lse)


def _merge(o_a, lse_a, o_b, lse_b):
    """Combine two partial attention results over the same queries.
    o: [B, H, T, D] fp32; lse: [B, H, T, 1] fp32. Skipped blocks carry
    lse = NEG_INF (-1e30, finite): after subtracting the max their weight
    underflows to exactly 0, so no special-casing is needed — the max side
    always contributes weight exp(0) = 1 and the denominator is >= 1."""
    m = jnp.maximum(lse_a, lse_b)
    w_a = jnp.exp(lse_a - m)
    w_b = jnp.exp(lse_b - m)
    denom = w_a + w_b
    o = (o_a * w_a + o_b * w_b) / denom
    return o, m + jnp.log(denom)


def ring_flash_attention(q, k, v, axis_name, causal=False, mask=None,
                         scale=None, block_q=None, block_k=None):
    """Flash attention over sequence shards on a ring. SPMD-collective:
    must run inside shard_map (or pmap) with ``axis_name`` bound, with
    q/k/v sequence dims sharded over that axis.

    Args:
      q, k, v: [B, H, T_local, D] — the local sequence shard.
      axis_name: mesh axis the sequence is sharded over.
      causal: causal masking in GLOBAL sequence positions (shards are
        assumed laid out in axis-index order).
      mask: optional additive key padding mask shard [B, T_local]
        (0 keep / -1e9 drop), covering this shard's KEY positions; it
        rotates with the k/v blocks.
      scale: score scale; default 1/sqrt(D).
      block_q/block_k: Pallas tile sizes for the local kernel.
    Returns: [B, H, T_local, D] in q.dtype.
    """
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    if n == 1:
        return flash_attention_with_lse(
            q, k, v, mask=mask, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k)[0]

    b, h, t_local, _ = q.shape
    o0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    lse0 = jnp.full((b, h, t_local, 1), NEG_INF, jnp.float32)
    has_mask = mask is not None
    # The mask occupies a scan-carry slot (rotating with its k/v block)
    # only when present — a dead zeros-mask would cost one extra ppermute
    # per ring step per layer.
    mask_carry = (mask.astype(jnp.float32),) if has_mask else ()
    # Ring neighbour: receive from the previous rank, send to the next, so
    # at step s the local device holds k/v block (my - s) mod n.
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        o, lse, k_blk, v_blk = carry[:4]
        cur_mask = carry[4] if has_mask else None
        src = (my - s) % n

        def full_block():
            oc, lc = flash_attention_with_lse(
                q, k_blk, v_blk, mask=cur_mask, causal=False, scale=scale,
                block_q=block_q, block_k=block_k)
            return oc.astype(jnp.float32), lc

        if causal:
            def diag_block():
                od, ld = flash_attention_with_lse(
                    q, k_blk, v_blk, mask=cur_mask, causal=True,
                    scale=scale, block_q=block_q, block_k=block_k)
                return od.astype(jnp.float32), ld

            def skipped_block():
                return jnp.zeros_like(o0), jnp.full_like(lse0, NEG_INF)

            # Block-level causality by ring step: src > my contributes
            # nothing (and its kernels never run — cond, not where).
            o_p, lse_p = jax.lax.cond(
                src > my, skipped_block,
                lambda: jax.lax.cond(src == my, diag_block, full_block))
        else:
            o_p, lse_p = full_block()
        o, lse = _merge(o, lse, o_p, lse_p)

        # Rotate k/v (+mask) for the next step. The final step's rotation
        # would be discarded — skip it (the predicate is the scan counter,
        # identical on every device, so the collective stays globally
        # consistent).
        def rotate(kvm):
            return tuple(jax.lax.ppermute(x, axis_name, perm) for x in kvm)

        rolling = (k_blk, v_blk) + ((cur_mask,) if has_mask else ())
        rolling = jax.lax.cond(s < n - 1, rotate, lambda kvm: kvm, rolling)
        return (o, lse) + rolling, None

    (o, lse, *_), _ = jax.lax.scan(step, (o0, lse0, k, v) + mask_carry,
                                   jnp.arange(n))
    return o.astype(q.dtype)


def sequence_parallel_attention(mesh, q, k, v, axis_name="data",
                                causal=False, mask=None, scale=None,
                                block_q=None, block_k=None):
    """shard_map wrapper: q/k/v are GLOBAL [B, H, T, D] arrays (or host
    numpy); the sequence dim is sharded over ``axis_name`` and attention
    runs as a ring. ``mask`` is the GLOBAL [B, T] additive key padding
    mask. Batch/head dims stay replicated here — compose with
    data-parallel batch sharding by calling ring_flash_attention directly
    inside your own shard_map."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    ring = functools.partial(ring_flash_attention, axis_name=axis_name,
                             causal=causal, scale=scale, block_q=block_q,
                             block_k=block_k)
    if mask is None:
        fn = shard_map(ring, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
        return fn(q, k, v)
    fn = shard_map(lambda q, k, v, m: ring(q, k, v, mask=m),
                   mesh=mesh,
                   in_specs=(spec, spec, spec, P(None, axis_name)),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v, mask)
