"""DeepSpeedTransformerLayer — the fused BERT-style encoder layer.

TPU-native re-design of the reference's fused transformer op
(deepspeed/ops/transformer/transformer.py:39 DeepSpeedTransformerConfig,
:153 DeepSpeedTransformerFunction, :260 DeepSpeedTransformerLayer; kernels in
csrc/transformer/ds_transformer_cuda.cpp:624 Forward / :809 Backward).

Same parameter surface (the 12 tensors: attn_qkvw/b, attn_ow/ob, attn_nw/nb,
inter_w/b, output_w/b, norm_w/b), same config knobs, but the execution is a
composition of Pallas kernels instead of a persistent C++ layer object:

  qkv GEMM -> flash attention (fused score GEMM+softmax+ctx GEMM, replacing
  launch_attn_softmax + cuBLAS strided-batch GEMMs) -> attn-out GEMM ->
  fused bias+dropout+residual -> fused LN -> FF1 GEMM -> fused bias+GELU ->
  FF2 GEMM -> fused bias+dropout+residual -> fused LN

The reference's per-layer-id object registry + shared workspace singleton
(csrc/includes/context.h:42-83) is unnecessary: XLA owns buffer reuse across
layers. Memory-saving config flags map to remat policies:
  normalize_invertible / gelu_checkpoint / attn_dropout_checkpoint
  -> jax.checkpoint over the matching sub-computation.
Sequence padding to a multiple of 16 (reference transformer.py:183-193)
becomes padding to the flash block size, handled inside flash_attention's
shape gate.
"""

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.kernels.attention import flash_attention
from deepspeed_tpu.ops.transformer.kernels.dropout import (
    dropout as ds_dropout, fused_bias_dropout_residual)
from deepspeed_tpu.ops.transformer.kernels.gelu import fused_bias_gelu
from deepspeed_tpu.ops.transformer.kernels.layer_norm import (
    fused_bias_residual_layer_norm, fused_layer_norm)


@dataclasses.dataclass
class DeepSpeedTransformerConfig:
    """Config surface of the reference DeepSpeedTransformerConfig
    (ops/transformer/transformer.py:39-150). local_rank is accepted for
    compatibility (single-controller JAX has no per-process rank here);
    stochastic_mode maps to the TPU precision-for-speed trade (fp32
    layers run attention on the bf16 kernel fast path — see _attention);
    fp16 selects bf16 compute on TPU unless fp16 is forced."""

    batch_size: int = -1
    max_seq_length: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1
    heads: int = -1
    attn_dropout_ratio: float = -1
    hidden_dropout_ratio: float = -1
    num_hidden_layers: int = -1
    initializer_range: float = -1
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    huggingface: bool = False
    training: bool = True
    # TPU-only: compute dtype (bf16 is the native fast path).
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.intermediate_size in (-1, None) and self.hidden_size > 0:
            self.intermediate_size = 4 * self.hidden_size

    @classmethod
    def from_dict(cls, json_object):
        config = cls()
        for key, value in json_object.items():
            if hasattr(config, key):
                setattr(config, key, value)
        config.__post_init__()
        return config

    @classmethod
    def from_json_file(cls, json_file):
        import json
        with open(json_file, "r", encoding="utf-8") as reader:
            return cls.from_dict(json.loads(reader.read()))


class DeepSpeedTransformerLayer(nn.Module):
    """Fused transformer layer (flax). Parameter names/shapes match the
    reference module (ops/transformer/transformer.py:269-309) so weights
    round-trip through module_inject repacking."""

    config: DeepSpeedTransformerConfig

    def setup(self):
        cfg = self.config
        h = cfg.hidden_size
        inter = cfg.intermediate_size
        std = cfg.initializer_range if cfg.initializer_range > 0 else 0.02
        out_std = std
        if cfg.adjust_init_range and cfg.num_hidden_layers > 0:
            # Output-projection init scaled by depth (reference
            # transformer.py:279-284 "output_std = std / sqrt(2L)").
            out_std = std / (2.0 * cfg.num_hidden_layers) ** 0.5
        ini = nn.initializers.normal
        self.attn_qkvw = self.param("attn_qkvw", ini(std), (3 * h, h), jnp.float32)
        self.attn_qkvb = self.param("attn_qkvb", nn.initializers.zeros, (3 * h,), jnp.float32)
        self.attn_ow = self.param("attn_ow", ini(out_std), (h, h), jnp.float32)
        self.attn_ob = self.param("attn_ob", nn.initializers.zeros, (h,), jnp.float32)
        self.attn_nw = self.param("attn_nw", nn.initializers.ones, (h,), jnp.float32)
        self.attn_nb = self.param("attn_nb", nn.initializers.zeros, (h,), jnp.float32)
        self.inter_w = self.param("inter_w", ini(std), (inter, h), jnp.float32)
        self.inter_b = self.param("inter_b", nn.initializers.zeros, (inter,), jnp.float32)
        self.output_w = self.param("output_w", ini(out_std), (h, inter), jnp.float32)
        self.output_b = self.param("output_b", nn.initializers.zeros, (h,), jnp.float32)
        self.norm_w = self.param("norm_w", nn.initializers.ones, (h,), jnp.float32)
        self.norm_b = self.param("norm_b", nn.initializers.zeros, (h,), jnp.float32)

    def _attention(self, x, attention_mask, seed, deterministic):
        cfg = self.config
        B, T, H = x.shape
        nh = cfg.heads
        hd = H // nh
        dt = cfg.dtype

        qkv = x @ self.attn_qkvw.astype(dt).T + self.attn_qkvb.astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
        # stochastic_mode: the reference registers distinct faster,
        # non-bit-reproducible TRAINING kernels for this flag
        # (csrc/transformer/ds_transformer_cuda.cpp:1011-1028); inference
        # is unaffected there, so eval stays exact here too. The TPU
        # equivalent trade is precision-for-speed: an fp32 layer drops its
        # attention to the bf16 kernel fast path (model-dtype exp, fused
        # MXU row-sum/delta — ops/transformer/kernels/attention.py). bf16
        # layers already take that path, matching the reference's note
        # that stochastic mode mainly pays off in half precision.
        stochastic_lowp = cfg.stochastic_mode and dt == jnp.float32 \
            and not deterministic
        if stochastic_lowp:
            q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))

        def attn_fn(q, k, v):
            ctx = flash_attention(q, k, v, mask=attention_mask, causal=False)
            if stochastic_lowp:
                ctx = ctx.astype(dt)
            if cfg.attn_dropout_ratio > 0 and not deterministic:
                # Flash never materialises probs, so attention dropout moves
                # to the context output (same regularisation role as
                # attn_dropout_checkpoint's recompute-in-backward).
                ctx = ds_dropout(ctx, cfg.attn_dropout_ratio, seed)
            return ctx
        if cfg.attn_dropout_checkpoint:
            attn_fn = jax.checkpoint(attn_fn)
        ctx = attn_fn(q, k, v)

        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, T, H)
        return ctx @ self.attn_ow.astype(dt).T

    def __call__(self, hidden_states, attention_mask=None, deterministic=None):
        """hidden_states: [B, T, H]; attention_mask: additive [B, T] padding
        mask (0 keep / large-negative drop), the reference's convention."""
        cfg = self.config
        if deterministic is None:
            deterministic = not cfg.training
        dt = cfg.dtype
        x = hidden_states.astype(dt)
        eps = cfg.layer_norm_eps
        # Dropout streams: when training under flax RNG plumbing, fold the
        # per-step dropout key into the kernel seed so masks differ every
        # step (the reference's advancing cuRAND state); otherwise fall back
        # to the static config seed (reproducible stochastic_mode-style).
        if not deterministic and self.has_rng("dropout"):
            seed = jax.random.bits(self.make_rng("dropout"),
                                   dtype=jnp.uint32).astype(jnp.int32)
        else:
            seed = cfg.seed if cfg.seed > 0 else 42
        # Distinct streams per dropout site, deterministic per layer+site.
        seeds = [seed + i for i in range(4)]

        if cfg.pre_layer_norm:
            h = fused_layer_norm(x, self.attn_nw, self.attn_nb, eps)
            attn_out = self._attention(h, attention_mask, seeds[0],
                                       deterministic)
            x = fused_bias_dropout_residual(
                attn_out, self.attn_ob, x, cfg.hidden_dropout_ratio,
                seeds[1], deterministic)
            h = fused_layer_norm(x, self.norm_w, self.norm_b, eps)
        else:
            attn_out = self._attention(x, attention_mask, seeds[0],
                                       deterministic)
            x = self._post_ln(attn_out, x, self.attn_ob, self.attn_nw,
                              self.attn_nb, cfg.hidden_dropout_ratio,
                              seeds[1], deterministic, eps)
            h = x

        def ff(h_in, res):
            ff1 = h_in @ self.inter_w.astype(dt).T
            act = fused_bias_gelu(ff1, self.inter_b)
            ff2 = act @ self.output_w.astype(dt).T
            if cfg.pre_layer_norm:
                return fused_bias_dropout_residual(
                    ff2, self.output_b, res, cfg.hidden_dropout_ratio,
                    seeds[2], deterministic)
            return self._post_ln(ff2, res, self.output_b, self.norm_w,
                                 self.norm_b, cfg.hidden_dropout_ratio,
                                 seeds[2], deterministic, eps)

        if cfg.gelu_checkpoint:
            ff = jax.checkpoint(ff)
        out = ff(h, x)
        return out

    def _post_ln(self, y, residual, bias, nw, nb, rate, seed, deterministic,
                 eps):
        # Post-LN epilogue: LN(dropout(y + bias) + residual) — the fused
        # bias_residual LN of normalize_kernels.cu:226.
        if rate > 0 and not deterministic:
            z = fused_bias_dropout_residual(y, bias, residual, rate, seed,
                                            deterministic)
            return fused_layer_norm(z, nw, nb, eps)
        return fused_bias_residual_layer_norm(y, residual, nw, nb, bias=bias,
                                              eps=eps)


def transformer_layer_reference(params, x, attention_mask, config):
    """Plain-jnp reference of the fused layer (parity oracle, mirroring how
    tests/unit/test_cuda_forward.py checks the CUDA layer against vendored
    BertLayer modeling code)."""
    from deepspeed_tpu.ops.transformer.kernels.attention import mha_reference
    from deepspeed_tpu.ops.transformer.kernels.gelu import bias_gelu_reference
    from deepspeed_tpu.ops.transformer.kernels.layer_norm import (
        layer_norm_reference)

    cfg = config
    dt = cfg.dtype
    B, T, H = x.shape
    nh = cfg.heads
    hd = H // nh
    p = {k: v.astype(dt) for k, v in params.items()}
    x = x.astype(dt)
    eps = cfg.layer_norm_eps

    def attention(h):
        qkv = h @ p["attn_qkvw"].T + p["attn_qkvb"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
        ctx = mha_reference(q, k, v, mask=attention_mask)
        return ctx.transpose(0, 2, 1, 3).reshape(B, T, H) @ p["attn_ow"].T

    if cfg.pre_layer_norm:
        h = layer_norm_reference(x, p["attn_nw"], p["attn_nb"], eps)
        x = x + attention(h) + p["attn_ob"]
        h = layer_norm_reference(x, p["norm_w"], p["norm_b"], eps)
        ff = bias_gelu_reference(h @ p["inter_w"].T, p["inter_b"])
        return x + ff @ p["output_w"].T + p["output_b"]
    x = layer_norm_reference(attention(x) + p["attn_ob"] + x,
                             p["attn_nw"], p["attn_nb"], eps)
    ff = bias_gelu_reference(x @ p["inter_w"].T, p["inter_b"])
    return layer_norm_reference(ff @ p["output_w"].T + p["output_b"] + x,
                                p["norm_w"], p["norm_b"], eps)
