"""FusedLamb: LAMB with per-tensor trust ratios as one fused XLA update.

TPU-native equivalent of reference csrc/lamb/fused_lamb_cuda_kernel.cu (469
LoC) + ops/lamb/fused_lamb.py:12. The CUDA kernel's two-phase structure
(per-tensor norm reduction, then trust-ratio-scaled update) maps onto two XLA
reduction/elementwise stages that the compiler schedules together; per-tensor
weight/update norms are exactly the LAMB trust-ratio inputs.

``max_coeff``/``min_coeff`` clamp the trust ratio like the reference kernel's
lamb_coeff bounds (fused_lamb_cuda.cpp:5-40).
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.fused_adam import _static_zero


def init_lamb_state(params):
    zeros_like = lambda p: jnp.zeros(p.shape, dtype=jnp.float32)
    return {
        "step": jnp.zeros((), dtype=jnp.int32),
        "exp_avg": jax.tree_util.tree_map(zeros_like, params),
        "exp_avg_sq": jax.tree_util.tree_map(zeros_like, params),
    }


def lamb_update(params,
                grads,
                state,
                lr,
                beta1=0.9,
                beta2=0.999,
                eps=1e-8,
                weight_decay=0.0,
                bias_correction=True,
                max_coeff=10.0,
                min_coeff=0.01):
    """One fused LAMB step over a pytree. Pure and jit-safe."""
    step = state["step"] + 1
    step_f = step.astype(jnp.float32)
    if bias_correction:
        bc1 = 1.0 - beta1 ** step_f
        bc2 = 1.0 - beta2 ** step_f
    else:
        bc1 = jnp.float32(1.0)
        bc2 = jnp.float32(1.0)

    def _update(p, g, m, v):
        g = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m_new = beta1 * m + (1.0 - beta1) * g
        v_new = beta2 * v + (1.0 - beta2) * jnp.square(g)
        update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if not _static_zero(weight_decay):
            update = update + weight_decay * p32
        # Phase 1: per-tensor norms (the reference's cub block reductions).
        w_norm = jnp.linalg.norm(p32.reshape(-1))
        u_norm = jnp.linalg.norm(update.reshape(-1))
        # Phase 2: trust-ratio scaled update.
        trust_ratio = jnp.where(
            (w_norm > 0) & (u_norm > 0),
            jnp.clip(w_norm / u_norm, min_coeff, max_coeff),
            1.0)
        p_new = p32 - lr * trust_ratio * update
        return p_new.astype(p.dtype), m_new, v_new

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state["exp_avg"])
    flat_v = treedef.flatten_up_to(state["exp_avg_sq"])

    new_p, new_m, new_v = [], [], []
    for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
        pn, mn, vn = _update(p, g, m, v)
        new_p.append(pn)
        new_m.append(mn)
        new_v.append(vn)

    new_params = jax.tree_util.tree_unflatten(treedef, new_p)
    new_state = {
        "step": step,
        "exp_avg": jax.tree_util.tree_unflatten(treedef, new_m),
        "exp_avg_sq": jax.tree_util.tree_unflatten(treedef, new_v),
    }
    return new_params, new_state


class FusedLamb(object):
    """LAMB optimizer façade matching reference ops/lamb/fused_lamb.py:12."""

    def __init__(self,
                 params=None,
                 lr=1e-3,
                 bias_correction=True,
                 betas=(0.9, 0.999),
                 eps=1e-8,
                 eps_inside_sqrt=False,
                 weight_decay=0.0,
                 max_grad_norm=0.0,
                 max_coeff=10.0,
                 min_coeff=0.01,
                 amsgrad=False):
        if amsgrad:
            raise RuntimeError("FusedLamb does not support the AMSGrad variant.")
        self.bias_correction = bias_correction
        self.eps_inside_sqrt = eps_inside_sqrt
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.param_groups = [{
            "params": params,
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
            "max_grad_norm": max_grad_norm,
        }]
        self.defaults = dict(self.param_groups[0])
        self.state = {}

    def init_state(self, params):
        return init_lamb_state(params)

    def update(self, params, grads, state, lr=None, betas=None, eps=None,
               weight_decay=None):
        group = self.param_groups[0]
        lr = group["lr"] if lr is None else lr
        beta1, beta2 = group["betas"] if betas is None else betas
        return lamb_update(params,
                           grads,
                           state,
                           lr=lr,
                           beta1=beta1,
                           beta2=beta2,
                           eps=group["eps"] if eps is None else eps,
                           weight_decay=group["weight_decay"]
                           if weight_decay is None else weight_decay,
                           bias_correction=self.bias_correction,
                           max_coeff=self.max_coeff,
                           min_coeff=self.min_coeff)

    def state_dict(self):
        return {"param_groups": [
            {k: v for k, v in g.items() if k != "params"}
            for g in self.param_groups]}

    def load_state_dict(self, sd):
        for group, saved in zip(self.param_groups, sd.get("param_groups", [])):
            group.update(saved)
