"""DeepSpeedCPULamb — host-memory LAMB for the ZeRO-Offload tier.

The reference ships LAMB only as a CUDA op (ops/lamb/fused_lamb.py:12,
csrc/lamb/fused_lamb_cuda_kernel.cu) and its offload tier is Adam-only
(engine.py:577-617 decision matrix). On the TPU-VM tier the host runs the
offloaded update either way, so LAMB gets the same C++ OpenMP treatment as
cpu_adam: per-tensor trust ratios computed in one parallel pass
(csrc/lamb/cpu_lamb.cpp), with the fused bf16 downcast variant.

Because LAMB's trust ratio is a PER-TENSOR statistic, the flat-buffer step
takes an optional ``segments`` list of (offset, size) spans — each span is
one parameter tensor and gets its own ratio. Without segments the whole
span is treated as a single tensor (matching FusedLamb called on one leaf).

Falls back to a vectorized numpy implementation when no C++ toolchain is
available (the OpBuilder contract: is_compatible() gates, never crashes).
"""

import numpy as np

from deepspeed_tpu.op_builder import CPULambBuilder
from deepspeed_tpu.op_builder.builder import as_c_float, as_c_u16
from deepspeed_tpu.utils.logging import logger


def _bf16_rne(x):
    """fp32 -> bf16 bits with round-to-nearest-even (matches the C++
    float_to_bf16, csrc/lamb/cpu_lamb.cpp)."""
    bits = np.ascontiguousarray(x, np.float32).view(np.uint32)
    nan = (bits & np.uint32(0x7fffffff)) > np.uint32(0x7f800000)
    lsb = (bits >> np.uint32(16)) & np.uint32(1)
    rounded = (bits + np.uint32(0x7fff) + lsb) >> np.uint32(16)
    quiet = (bits >> np.uint32(16)) | np.uint32(0x0040)
    return np.where(nan, quiet, rounded).astype(np.uint16)


class DeepSpeedCPULamb(object):
    """Host LAMB with the DeepSpeedCPUAdam step_flat contract, so the
    engine's ZeRO-Offload pipeline (chunked copy / OpenMP step / async
    upload) drives it unchanged."""

    supports_segments = True
    optimizer_id = 0

    def __init__(self,
                 model_params=None,
                 lr=1e-3,
                 bias_correction=True,
                 betas=(0.9, 0.999),
                 eps=1e-8,
                 weight_decay=0.0,
                 max_coeff=10.0,
                 min_coeff=0.01,
                 amsgrad=False):
        if amsgrad:
            raise RuntimeError("CPULamb does not support the AMSGrad variant.")
        self.opt_id = DeepSpeedCPULamb.optimizer_id
        DeepSpeedCPULamb.optimizer_id += 1
        self.bias_correction = bias_correction
        self.max_coeff = float(max_coeff)
        self.min_coeff = float(min_coeff)
        self.param_groups = [{
            "params": model_params,
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
        }]
        self.defaults = {k: v for k, v in self.param_groups[0].items()
                         if k != "params"}
        self.state = {}
        self._step = 0
        self.lamb_coeffs = []  # last step's trust ratios (reference
        # fused_lamb_cuda.cpp:42-56 get_lamb_coeffs introspection)
        self._coeffs_step = None  # step the coeffs accumulator belongs to

        builder = CPULambBuilder()
        self.ds_opt_lamb = None
        if builder.is_compatible():
            try:
                self.ds_opt_lamb = builder.load()
            except (RuntimeError, OSError) as e:
                logger.warning("cpu_lamb build failed (%s); "
                               "using numpy fallback", e)
        else:
            logger.warning("cpu_lamb op incompatible (%s); "
                           "using numpy fallback", builder.compatible_reason())

    # ------------------------------------------------------------- core step
    def step_flat(self, params, grads, exp_avg, exp_avg_sq, step=None,
                  lr=None, bf16_out=None, segments=None):
        """One LAMB step over contiguous fp32 numpy buffers, in place.

        segments: optional [(offset, size), ...] spans — one trust-ratio
        domain each (a parameter tensor). Defaults to one span over the
        whole buffer.
        """
        group = self.param_groups[0]
        if step is None:
            self._step += 1
            step = self._step
        lr = group["lr"] if lr is None else lr
        beta1, beta2 = group["betas"]
        eps = group["eps"]
        wd = group["weight_decay"]
        assert params.dtype == np.float32 and grads.dtype == np.float32
        if segments is None:
            segments = [(0, params.size)]

        # The engine's offload pipeline calls step_flat once per transfer
        # chunk with the same `step`; coeffs accumulate across those calls
        # and reset when a new optimizer step begins, so get_lamb_coeffs()
        # always covers ALL tensors of the latest step (reference
        # fused_lamb_cuda.cpp:42-56 semantics).
        if step != self._coeffs_step:
            self.lamb_coeffs = []
            self._coeffs_step = step
        for off, size in segments:
            sl = slice(off, off + size)
            ratio = self._step_span(
                params[sl], grads[sl], exp_avg[sl], exp_avg_sq[sl],
                step, lr, beta1, beta2, eps, wd,
                None if bf16_out is None else bf16_out[sl])
            self.lamb_coeffs.append(ratio)

    def _step_span(self, p, g, m, v, step, lr, beta1, beta2, eps, wd,
                   bf16_out):
        if self.ds_opt_lamb is not None:
            scratch = np.empty_like(p)
            return float(self.ds_opt_lamb.ds_lamb_step(
                step, lr, beta1, beta2, eps, wd,
                int(self.bias_correction), self.max_coeff, self.min_coeff,
                p.size, as_c_float(p), as_c_float(g), as_c_float(m),
                as_c_float(v), as_c_float(scratch), as_c_u16(bf16_out)))

        # numpy fallback (same math)
        np.multiply(m, beta1, out=m)
        m += (1.0 - beta1) * g
        np.multiply(v, beta2, out=v)
        v += (1.0 - beta2) * np.square(g)
        if self.bias_correction:
            bc1 = 1.0 - beta1 ** step
            bc2 = 1.0 - beta2 ** step
        else:
            bc1, bc2 = 1.0, 1.0
        update = (m / bc1) / (np.sqrt(v / bc2) + eps)
        if wd > 0.0:
            update = update + wd * p
        w_norm = float(np.linalg.norm(p))
        u_norm = float(np.linalg.norm(update))
        ratio = 1.0
        if w_norm > 0.0 and u_norm > 0.0:
            ratio = min(max(w_norm / u_norm, self.min_coeff), self.max_coeff)
        p -= lr * ratio * update
        if bf16_out is not None:
            bf16_out[:] = _bf16_rne(p)
        return ratio

    def get_lamb_coeffs(self):
        return list(self.lamb_coeffs)

    # --------------------------------------------------- torch-style surface
    def step(self, closure=None):
        """Reference-style step over param_groups of
        {'params': np_array, 'grads': np_array} dicts."""
        loss = None
        if closure is not None:
            loss = closure()
        self._step += 1
        self.lamb_coeffs = []
        for gi, group in enumerate(self.param_groups):
            for pi, p in enumerate(group.get("params") or []):
                if not isinstance(p, dict) or p.get("grads") is None:
                    continue
                key = (gi, pi)
                if key not in self.state:
                    self.state[key] = {
                        "exp_avg": np.zeros_like(p["params"]),
                        "exp_avg_sq": np.zeros_like(p["params"]),
                    }
                st = self.state[key]
                for name in ("params", "grads"):
                    if not p[name].flags["C_CONTIGUOUS"]:
                        raise ValueError(
                            "CPULamb.step requires C-contiguous {} arrays "
                            "(got a strided view; use np.ascontiguousarray)"
                            .format(name))
                ratio = self._step_span(
                    p["params"].ravel(), p["grads"].ravel(),
                    st["exp_avg"].ravel(), st["exp_avg_sq"].ravel(),
                    self._step, group["lr"], *group["betas"],
                    group["eps"], group["weight_decay"], None)
                self.lamb_coeffs.append(ratio)
        return loss
