"""Per-shape kernel autotuner — the TPU analog of the reference's GEMM
algorithm sweeps (csrc/includes/gemm_test.h:27,141: GemmTest/StridedGemmTest
try every cublas algo at layer construction and pick the fastest).

On TPU the tunable axis is Pallas tile sizes, not cublas algos. Selection
order per (kernel, shape-signature) key:

1. in-process memo;
2. a bundled offline table shipped with the package (tuned on real
   hardware, keyed by platform);
3. a user cache file (~/.cache/deepspeed_tpu/autotune.json), populated by
   online sweeps;
4. when ``DS_TPU_AUTOTUNE=1``, an online sweep: time every candidate with
   compile excluded (one warmup, then min of ``repeats``), persist the
   winner to the user cache. Otherwise: the caller's default.

Online sweeps cost one kernel compile per candidate (~20-40 s each on a
cold remote-compile tunnel), so they are opt-in — like the reference, which
also pays its sweep at layer creation, not silently per step.
"""

import json
import os
import time

import jax

_MEMO = {}
_BUNDLED = None
_USER = None

_BUNDLED_PATH = os.path.join(os.path.dirname(__file__), "autotune_table.json")


def _user_cache_path():
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "deepspeed_tpu", "autotune.json")


def _load(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _tables():
    global _BUNDLED, _USER
    if _BUNDLED is None:
        _BUNDLED = _load(_BUNDLED_PATH)
    if _USER is None:
        _USER = _load(_user_cache_path())
    return _BUNDLED, _USER


def online_enabled():
    return os.environ.get("DS_TPU_AUTOTUNE", "0") not in ("0", "", "false")


def force_enabled():
    """DS_TPU_AUTOTUNE=force: re-sweep even for shapes already in a table
    (used to refresh stale tables after a kernel redesign changes the
    cost surface). Winners still land in the user cache."""
    return os.environ.get("DS_TPU_AUTOTUNE", "") == "force"


def _sync(out):
    """Execution barrier via a scalar VALUE fetch: on remote-device
    platforms block_until_ready can return before execution finishes, which
    would time async dispatch instead of the kernel."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(leaf.ravel()[0].astype("float32"))


def _time_candidate(run, repeats):
    _sync(run())  # compile + warmup
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        _sync(run())
        best = min(best, time.perf_counter() - t0)
    return best


# Hardware tile quantum per kernel family: every block size in a table
# entry must be a positive multiple of its family's minimum (one 128-lane
# row). Families not listed here only get the positive-int check.
_KERNEL_MIN_BLOCK = {
    "flash_attention": 128,
    "decode_attention": 128,
    "decode_attention_q8": 128,
}


def validate_table(table, source="autotune table"):
    """Schema-check a tile table (the bundled file or a user cache dump):
    every key must parse as ``platform::kernel::signature`` with non-empty
    parts, every entry must be a dict with a ``choice`` list of positive
    ints, and kernels with a known tile quantum (_KERNEL_MIN_BLOCK)
    additionally require each block to be a positive multiple of it.
    Raises ValueError naming the offending key; returns the number of
    entries checked. Guards hand-edits from hardware sweeps — a malformed
    entry would otherwise break kernel dispatch at serving time
    (tests/unit/test_autotune_table.py runs this over the bundled file)."""
    if not isinstance(table, dict):
        raise ValueError("{}: expected a JSON object at top level, got "
                         "{}".format(source, type(table).__name__))
    for key, entry in table.items():
        parts = key.split("::")
        if len(parts) != 3 or not all(parts):
            raise ValueError(
                "{}: key {!r} does not parse as "
                "platform::kernel::signature".format(source, key))
        kernel = parts[1]
        if not isinstance(entry, dict) or "choice" not in entry:
            raise ValueError(
                "{}: entry for {!r} must be an object with a 'choice' "
                "list".format(source, key))
        choice = entry["choice"]
        blocks = choice if isinstance(choice, list) else [choice]
        if not blocks:
            raise ValueError(
                "{}: entry for {!r} has an empty choice".format(source, key))
        min_block = _KERNEL_MIN_BLOCK.get(kernel)
        for blk in blocks:
            if isinstance(blk, bool) or not isinstance(blk, int) or blk <= 0:
                raise ValueError(
                    "{}: entry for {!r} has non-positive-int block "
                    "{!r}".format(source, key, blk))
            if min_block and blk % min_block:
                raise ValueError(
                    "{}: entry for {!r} has block {} not a multiple of "
                    "{}'s minimum {}".format(source, key, blk, kernel,
                                             min_block))
    return len(table)


def table_key(kernel, signature):
    """The full table key for (current backend, kernel, signature) —
    the single place the key format lives, so sweep/promotion scripts
    (tests/perf/autotune_sweep.py) cannot drift from it."""
    return "{}::{}::{}".format(jax.default_backend(), kernel, signature)


def autotune(kernel, signature, candidates, make_run, default, repeats=3):
    """Pick the best candidate for (kernel, signature).

    Args:
      kernel: kernel family name, e.g. "flash_attention".
      signature: hashable shape signature, e.g. "b8_h16_t1024_d64_bf16".
      candidates: list of JSON-able candidate configs.
      make_run: candidate -> zero-arg callable executing the kernel once
        (only called during an online sweep).
      default: returned when no table entry exists and online tuning is off.
    Returns: the chosen candidate.
    """
    platform = jax.default_backend()
    key = table_key(kernel, signature)
    if key in _MEMO:
        return _MEMO[key]
    multiproc = jax.process_count() > 1
    bundled, user = _tables()
    # Multi-controller runs consult ONLY the package-bundled table: every
    # host ships the same file, so every host traces the same tiles. The
    # per-host user cache (and per-host sweeps) could diverge across hosts
    # and compile different executables.
    tables = (bundled,) if multiproc else (user, bundled)
    # force mode only bypasses the tables when a sweep can ACTUALLY run
    # here (eager call, runnable candidates, one controller, on-TPU);
    # otherwise — e.g. the engine's traced calls under
    # DS_TPU_AUTOTUNE=force — tuned tiles must still be served.
    can_sweep = (platform == "tpu" and len(candidates) > 1
                 and not multiproc)
    if not (force_enabled() and can_sweep):
        for table in tables:
            if key in table:
                chosen = table[key]["choice"]
                _MEMO[key] = chosen
                return chosen
    if not (online_enabled() and platform == "tpu" and len(candidates) > 1
            and not multiproc):
        if not online_enabled():
            # With tuning off the answer can never change — memoize. With
            # tuning ON but no runnable candidates (traced call), leave the
            # memo empty so a later EAGER call can still run the sweep.
            _MEMO[key] = default
        return default

    results = []
    errors = []
    for cand in candidates:
        try:
            dt = _time_candidate(make_run(cand), repeats)
        except Exception as e:  # candidate may not fit VMEM — skip it
            errors.append(str(e))
            continue
        results.append((dt, cand))
    if not results:
        if errors:
            # The user asked for tuning and got none — say so instead of
            # silently memoizing the default.
            import warnings
            warnings.warn(
                "autotune({}, {}): all {} candidates failed (first error: "
                "{}); using default {}".format(kernel, signature,
                                               len(candidates), errors[0],
                                               default))
        _MEMO[key] = default
        return default
    best_dt, best = min(results, key=lambda r: r[0])
    _MEMO[key] = best
    path = _user_cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        user = _load(path)
        user[key] = {"choice": best, "seconds": best_dt,
                     "candidates_timed": len(results)}
        tmp = "{}.tmp.{}".format(path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(user, f, indent=1, sort_keys=True)
        os.replace(tmp, path)  # atomic: concurrent writers can't corrupt
        global _USER
        _USER = user
    except OSError:
        pass
    return best
