"""FusedAdam: Adam/AdamW as one fused XLA update over the whole param pytree.

TPU-native equivalent of the reference's multi-tensor CUDA Adam
(csrc/adam/multi_tensor_adam.cu:123, ops/adam/fused_adam.py:15): instead of a
chunked multi-tensor kernel launch, the entire pytree update is traced into a
single jitted program — XLA fuses the elementwise Adam math across tensors, so
one executable updates all parameters with no per-tensor launch overhead (the
exact problem multi_tensor_apply solves on GPU).

The class carries torch-style ``param_groups`` (lr/betas/eps/weight_decay) so
LR schedulers and the engine's optimizer plumbing match the reference; the
numerical core is the pure function :func:`adam_update`.
"""

import jax
import jax.numpy as jnp


def _static_zero(x):
    """True only for a compile-time zero: a TRACED weight_decay (the
    pipeline engine threads it as a jit argument) must always apply the
    decay term — `tracer != 0` cannot be branched on at trace time."""
    return isinstance(x, (int, float)) and x == 0.0


def init_adam_state(params):
    """Zero first/second moments + step counter for a param pytree."""
    zeros_like = lambda p: jnp.zeros(p.shape, dtype=jnp.float32)
    return {
        "step": jnp.zeros((), dtype=jnp.int32),
        "exp_avg": jax.tree_util.tree_map(zeros_like, params),
        "exp_avg_sq": jax.tree_util.tree_map(zeros_like, params),
    }


def adam_update(params,
                grads,
                state,
                lr,
                beta1=0.9,
                beta2=0.999,
                eps=1e-8,
                weight_decay=0.0,
                adam_w_mode=True,
                bias_correction=True):
    """One fused Adam/AdamW step over a pytree. Pure and jit-safe.

    adam_w_mode=True → decoupled weight decay (AdamW); False → L2-style decay
    added to the gradient (classic Adam), matching the reference kernel's
    ``adam_w_mode`` switch (multi_tensor_adam.cu:84-118).
    """
    step = state["step"] + 1
    step_f = step.astype(jnp.float32)
    if bias_correction:
        bc1 = 1.0 - beta1 ** step_f
        bc2 = 1.0 - beta2 ** step_f
    else:
        bc1 = jnp.float32(1.0)
        bc2 = jnp.float32(1.0)

    def _update(p, g, m, v):
        g = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if not adam_w_mode and not _static_zero(weight_decay):
            g = g + weight_decay * p32
        m_new = beta1 * m + (1.0 - beta1) * g
        v_new = beta2 * v + (1.0 - beta2) * jnp.square(g)
        denom = jnp.sqrt(v_new / bc2) + eps
        update = (m_new / bc1) / denom
        if adam_w_mode and not _static_zero(weight_decay):
            update = update + weight_decay * p32
        p_new = p32 - lr * update
        return p_new.astype(p.dtype), m_new, v_new

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state["exp_avg"])
    flat_v = treedef.flatten_up_to(state["exp_avg_sq"])

    new_p, new_m, new_v = [], [], []
    for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
        pn, mn, vn = _update(p, g, m, v)
        new_p.append(pn)
        new_m.append(mn)
        new_v.append(vn)

    new_params = jax.tree_util.tree_unflatten(treedef, new_p)
    new_state = {
        "step": step,
        "exp_avg": jax.tree_util.tree_unflatten(treedef, new_m),
        "exp_avg_sq": jax.tree_util.tree_unflatten(treedef, new_v),
    }
    return new_params, new_state


class FusedAdam(object):
    """Adam/AdamW optimizer façade matching reference ops/adam/fused_adam.py:15.

    Stateless w.r.t. tensors — the engine owns (params, state) pytrees and
    calls :meth:`update` inside its jitted step. ``param_groups`` exists for
    scheduler compatibility (single group; per-group partitioning of pytrees
    arrives with the ZeRO work).
    """

    def __init__(self,
                 params=None,
                 lr=1e-3,
                 bias_correction=True,
                 betas=(0.9, 0.999),
                 eps=1e-8,
                 adam_w_mode=True,
                 weight_decay=0.0,
                 amsgrad=False,
                 set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction
        self.set_grad_none = set_grad_none
        self.param_groups = [{
            "params": params,
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
        }]
        self.defaults = {
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
        }
        self.state = {}

    def init_state(self, params):
        return init_adam_state(params)

    def update(self, params, grads, state, lr=None, betas=None, eps=None,
               weight_decay=None):
        group = self.param_groups[0]
        lr = group["lr"] if lr is None else lr
        beta1, beta2 = group["betas"] if betas is None else betas
        return adam_update(params,
                           grads,
                           state,
                           lr=lr,
                           beta1=beta1,
                           beta2=beta2,
                           eps=group["eps"] if eps is None else eps,
                           weight_decay=group["weight_decay"]
                           if weight_decay is None else weight_decay,
                           adam_w_mode=self.adam_w_mode,
                           bias_correction=self.bias_correction)

    def state_dict(self):
        return {"param_groups": [
            {k: v for k, v in g.items() if k != "params"}
            for g in self.param_groups]}

    def load_state_dict(self, sd):
        for group, saved in zip(self.param_groups, sd.get("param_groups", [])):
            group.update(saved)
