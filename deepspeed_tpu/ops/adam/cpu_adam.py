"""DeepSpeedCPUAdam — host-memory Adam for the ZeRO-Offload tier.

API mirror of the reference (deepspeed/ops/adam/cpu_adam.py:12: 5-7x faster
than torch.optim.Adam via AVX+OpenMP; ``step(fp16_param_groups=...)`` fuses
the downcast copy for +30%). Here the native core is the C++ op built by
CPUAdamBuilder (csrc/adam/cpu_adam.cpp) bound via ctypes, operating on
contiguous fp32 numpy buffers; ``step(..., bf16_out=...)`` is the fused
downcast variant (bf16 being the TPU compute dtype, where the reference
copies to fp16 CUDA params).

Falls back to a vectorized numpy implementation when no C++ toolchain is
available (the OpBuilder contract: is_compatible() gates, never crashes).
"""

import numpy as np

from deepspeed_tpu.op_builder import CPUAdamBuilder
from deepspeed_tpu.op_builder.builder import as_c_float as _as_c
from deepspeed_tpu.op_builder.builder import as_c_u16 as _as_c_u16
from deepspeed_tpu.utils.logging import logger


class DeepSpeedCPUAdam(object):
    optimizer_id = 0

    def __init__(self,
                 model_params=None,
                 lr=1e-3,
                 bias_correction=True,
                 betas=(0.9, 0.999),
                 eps=1e-8,
                 weight_decay=0.0,
                 amsgrad=False,
                 adamw_mode=True):
        if amsgrad:
            raise RuntimeError("CPUAdam does not support the AMSGrad variant.")
        self.opt_id = DeepSpeedCPUAdam.optimizer_id
        DeepSpeedCPUAdam.optimizer_id += 1
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        self.param_groups = [{
            "params": model_params,
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
        }]
        self.defaults = {k: v for k, v in self.param_groups[0].items()
                         if k != "params"}
        self.state = {}
        self._step = 0

        builder = CPUAdamBuilder()
        self.ds_opt_adam = None
        if builder.is_compatible():
            try:
                self.ds_opt_adam = builder.load()
            except (RuntimeError, OSError) as e:  # build or dlopen failed
                logger.warning("cpu_adam build failed (%s); "
                               "using numpy fallback", e)
        else:
            logger.warning("cpu_adam op incompatible (%s); "
                           "using numpy fallback", builder.compatible_reason())

    # ------------------------------------------------------------- core step
    def step_flat(self, params, grads, exp_avg, exp_avg_sq, step=None,
                  lr=None, bf16_out=None):
        """One Adam step over contiguous fp32 numpy buffers, in place.

        params/grads/exp_avg/exp_avg_sq: 1-D float32 arrays of equal length.
        bf16_out: optional uint16 array; filled with bf16(params) fused into
        the same pass (the reference's fp16_param_groups copy fusion).
        """
        group = self.param_groups[0]
        if step is None:
            self._step += 1
            step = self._step
        lr = group["lr"] if lr is None else lr
        beta1, beta2 = group["betas"]
        eps = group["eps"]
        wd = group["weight_decay"]
        n = params.size
        assert params.dtype == np.float32 and grads.dtype == np.float32

        if self.ds_opt_adam is not None:
            if bf16_out is not None:
                self.ds_opt_adam.ds_adam_step_copy_bf16(
                    step, lr, beta1, beta2, eps, wd,
                    int(self.adamw_mode), int(self.bias_correction), n,
                    _as_c(params), _as_c(grads), _as_c(exp_avg),
                    _as_c(exp_avg_sq), _as_c_u16(bf16_out))
            else:
                self.ds_opt_adam.ds_adam_step(
                    step, lr, beta1, beta2, eps, wd,
                    int(self.adamw_mode), int(self.bias_correction), n,
                    _as_c(params), _as_c(grads), _as_c(exp_avg),
                    _as_c(exp_avg_sq))
            return

        # numpy fallback (same math)
        g = grads
        if not self.adamw_mode and wd > 0.0:
            g = g + wd * params
        np.multiply(exp_avg, beta1, out=exp_avg)
        exp_avg += (1.0 - beta1) * g
        np.multiply(exp_avg_sq, beta2, out=exp_avg_sq)
        exp_avg_sq += (1.0 - beta2) * np.square(g)
        if self.bias_correction:
            bc1 = 1.0 - beta1 ** step
            bc2s = np.sqrt(1.0 - beta2 ** step)
        else:
            bc1, bc2s = 1.0, 1.0
        update = exp_avg / bc1 / (np.sqrt(exp_avg_sq) / bc2s + eps)
        if self.adamw_mode and wd > 0.0:
            update = update + wd * params
        params -= lr * update
        if bf16_out is not None:
            # Truncating downcast (the C++ path rounds to nearest even).
            bf16_out[:] = (params.view(np.uint32) >> 16).astype(np.uint16)

    def l2_norm(self, arr):
        """Host-side grad norm (C++ reduction when available)."""
        if self.ds_opt_adam is not None:
            return float(np.sqrt(self.ds_opt_adam.ds_l2_norm_sq(arr.size,
                                                                _as_c(arr))))
        return float(np.linalg.norm(arr))

    def scale_(self, arr, alpha):
        if self.ds_opt_adam is not None:
            self.ds_opt_adam.ds_scale(arr.size, float(alpha), _as_c(arr))
        else:
            arr *= alpha

    # --------------------------------------------------- torch-style surface
    def step(self, closure=None, fp16_param_groups=None):
        """Reference signature (cpu_adam.py:77). Operates on param_groups
        whose 'params' are dicts {'params': np_array, 'grads': np_array}; the
        engine's offload path uses :meth:`step_flat` directly instead."""
        loss = None
        if closure is not None:
            loss = closure()
        self._step += 1
        for gi, group in enumerate(self.param_groups):
            params = group.get("params") or []
            for pi, p in enumerate(params):
                if not isinstance(p, dict) or p.get("grads") is None:
                    continue
                # Keyed by (group index, position) — stable when the caller
                # rebuilds the param dicts between steps; id(p) could be
                # silently reused after GC and cross-wire moments.
                key = (gi, pi)
                if key not in self.state:
                    self.state[key] = {
                        "exp_avg": np.zeros_like(p["params"]),
                        "exp_avg_sq": np.zeros_like(p["params"]),
                    }
                st = self.state[key]
                for name in ("params", "grads"):
                    if not p[name].flags["C_CONTIGUOUS"]:
                        # ravel() on a non-contiguous array copies; the
                        # in-place update would land in the temporary.
                        raise ValueError(
                            "CPUAdam.step requires C-contiguous {} arrays "
                            "(got a strided view; use np.ascontiguousarray)"
                            .format(name))
                self.step_flat(p["params"].ravel(), p["grads"].ravel(),
                               st["exp_avg"].ravel(),
                               st["exp_avg_sq"].ravel(), step=self._step,
                               lr=group["lr"])
        return loss
