from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
