from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer)
