from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer)
from deepspeed_tpu.ops import sparse_attention  # noqa: F401
