"""Op registry (reference op_builder/__init__.py:12-21 ALL_OPS).

Device-side ops (transformer/LN/softmax/dropout/GELU/sparse attention) are
Pallas kernels — no build step, registered for ds_report parity. Host ops
(cpu_adam, utils) are C++ compiled at first use.
"""

from deepspeed_tpu.op_builder.builder import (CPUAdamBuilder, CPULambBuilder,
                                              OpBuilder, SparseLutBuilder,
                                              UtilsBuilder, csrc_path)


class PallasOpBuilder(OpBuilder):
    """No-op builder for kernels that ship as Pallas (compiled by XLA at
    trace time). Exists so ALL_OPS / ds_report cover every reference op."""

    def __init__(self, name, module_path):
        super().__init__(name)
        self.module_path = module_path

    def sources(self):
        return []

    def is_compatible(self):
        return True

    def jit_load(self, verbose=True):
        import importlib
        return importlib.import_module(self.module_path)


def _pallas(name, module_path):
    return lambda: PallasOpBuilder(name, module_path)


ALL_OPS = {
    "cpu_adam": CPUAdamBuilder,
    "cpu_lamb": CPULambBuilder,
    "sparse_lut": SparseLutBuilder,
    "utils": UtilsBuilder,
    "fused_adam": _pallas("fused_adam", "deepspeed_tpu.ops.adam.fused_adam"),
    "fused_lamb": _pallas("fused_lamb", "deepspeed_tpu.ops.lamb.fused_lamb"),
    "transformer": _pallas("transformer",
                           "deepspeed_tpu.ops.transformer.transformer"),
    "stochastic_transformer": _pallas(
        "stochastic_transformer", "deepspeed_tpu.ops.transformer.transformer"),
    "sparse_attn": _pallas("sparse_attn",
                           "deepspeed_tpu.ops.sparse_attention.kernels"),
}
