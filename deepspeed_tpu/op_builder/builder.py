"""Op build system — JIT compilation of native host ops.

API mirror of the reference's OpBuilder (reference op_builder/builder.py:78:
``name``, ``sources()``, ``include_paths()``, ``is_compatible()``,
``load()``/``jit_load()``; registry in __init__.py:12-21). The reference
builds CUDA extensions with torch cpp_extension + ninja; here ops are plain
C++ shared objects compiled with g++ and bound through ctypes (no pybind11 in
the image), because on TPU the only native tier is *host* code — device
kernels are Pallas and need no build step.

Build artifacts are cached under ``$DS_BUILD_DIR`` (default
``~/.cache/deepspeed_tpu/ops``) keyed by a hash of the sources and flags, so
repeat loads are instant and source edits trigger rebuilds (same contract as
torch's JIT extension cache).
"""

import ctypes
import hashlib
import os
import shutil
import subprocess
import time

from deepspeed_tpu.utils.logging import logger

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "csrc")


def csrc_path(*parts):
    return os.path.join(_CSRC, *parts)


class OpBuilder(object):
    def __init__(self, name):
        self.name = name
        self._loaded = None

    # ---- interface mirrored from reference op_builder/builder.py:78-168
    def absolute_name(self):
        return "deepspeed_tpu.ops.{}".format(self.name)

    def sources(self):
        raise NotImplementedError

    def include_paths(self):
        return []

    def cxx_args(self):
        return ["-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
                "-march=native", "-Wall"]

    def is_compatible(self):
        return shutil.which("g++") is not None

    def compatible_reason(self):
        if shutil.which("g++") is None:
            return "g++ not found in PATH"
        return "compatible"

    # ---- build machinery
    def _build_dir(self):
        root = os.environ.get(
            "DS_BUILD_DIR",
            os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu",
                         "ops"))
        path = os.path.join(root, self.name)
        os.makedirs(path, exist_ok=True)
        return path

    def _signature(self):
        h = hashlib.sha1()
        for src in self.sources():
            with open(src, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.cxx_args()).encode())
        return h.hexdigest()[:16]

    def lib_path(self):
        return os.path.join(self._build_dir(),
                            "lib{}_{}.so".format(self.name, self._signature()))

    def jit_load(self, verbose=True, _retry=True):
        """Compile (if needed) and dlopen the op (reference builder.py:182-220)."""
        if not self.is_compatible():
            raise RuntimeError(
                "Unable to JIT load the {} op due to: {}".format(
                    self.name, self.compatible_reason()))
        lib = self.lib_path()
        if not os.path.exists(lib):
            start = time.time()
            # Compile to a tmp path and atomically rename so an interrupted
            # or concurrent build can never leave a truncated .so at the
            # final path (which would be dlopen'd forever).
            tmp = "{}.tmp{}".format(lib, os.getpid())
            cmd = (["g++"] + self.cxx_args() +
                   ["-I{}".format(p) for p in self.include_paths()] +
                   list(self.sources()) + ["-o", tmp])
            if verbose:
                logger.info("Building op %s: %s", self.name, " ".join(cmd))
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                os.replace(tmp, lib)
            except subprocess.CalledProcessError as e:
                raise RuntimeError(
                    "Failed to build op {}:\n{}".format(self.name, e.stderr))
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            if verbose:
                logger.info("Time to load %s op: %.3fs", self.name,
                            time.time() - start)
        try:
            return self._bind(ctypes.CDLL(lib))
        except OSError as e:
            if not _retry:
                # Fresh build still won't dlopen (ABI/linker issue, missing
                # runtime lib): surface as RuntimeError so callers' numpy
                # fallbacks engage instead of looping on rebuilds.
                raise RuntimeError(
                    "op {} built but cannot be loaded: {}".format(
                        self.name, e))
            # Corrupt cache entry (e.g. from a pre-atomic-rename build):
            # drop it and rebuild once.
            logger.warning("Cached op %s unloadable (%s); rebuilding", lib, e)
            os.unlink(lib)
            return self.jit_load(verbose=verbose, _retry=False)

    def load(self, verbose=True):
        if self._loaded is None:
            self._loaded = self.jit_load(verbose=verbose)
        return self._loaded

    def _bind(self, cdll):
        """Attach argtypes/restypes; override per op. Returns the module-like
        object handed to callers."""
        return cdll


_c_float_p = ctypes.POINTER(ctypes.c_float)
_c_u16_p = ctypes.POINTER(ctypes.c_uint16)
_c_long_p = ctypes.POINTER(ctypes.c_long)


def as_c_float(arr):
    """numpy fp32 array -> C float* (shared by the ctypes op wrappers)."""
    return arr.ctypes.data_as(_c_float_p)


def as_c_u16(arr):
    """numpy uint16 array -> C uint16_t*; None -> NULL."""
    if arr is None:
        return _c_u16_p()
    return arr.ctypes.data_as(_c_u16_p)


class CPUAdamBuilder(OpBuilder):
    """Builds the host Adam op (reference op_builder/cpu_adam.py)."""

    BUILD_VAR = "DS_BUILD_CPU_ADAM"
    NAME = "cpu_adam"

    def __init__(self):
        super().__init__(self.NAME)

    def sources(self):
        return [csrc_path("adam", "cpu_adam.cpp")]

    def _bind(self, cdll):
        scalar = [ctypes.c_long, ctypes.c_float, ctypes.c_float,
                  ctypes.c_float, ctypes.c_float, ctypes.c_float,
                  ctypes.c_int, ctypes.c_int, ctypes.c_long]
        cdll.ds_adam_step.argtypes = scalar + [_c_float_p] * 4
        cdll.ds_adam_step.restype = None
        cdll.ds_adam_step_copy_bf16.argtypes = scalar + [_c_float_p] * 4 + \
            [_c_u16_p]
        cdll.ds_adam_step_copy_bf16.restype = None
        cdll.ds_l2_norm_sq.argtypes = [ctypes.c_long, _c_float_p]
        cdll.ds_l2_norm_sq.restype = ctypes.c_double
        cdll.ds_scale.argtypes = [ctypes.c_long, ctypes.c_float, _c_float_p]
        cdll.ds_scale.restype = None
        return cdll


class CPULambBuilder(OpBuilder):
    """Builds the host LAMB op (reference builds LAMB as a CUDA op,
    op_builder/fused_lamb.py; the host variant makes Lamb + cpu_offload
    compose on the TPU-VM tier)."""

    BUILD_VAR = "DS_BUILD_CPU_LAMB"
    NAME = "cpu_lamb"

    def __init__(self):
        super().__init__(self.NAME)

    def sources(self):
        return [csrc_path("lamb", "cpu_lamb.cpp")]

    def _bind(self, cdll):
        cdll.ds_lamb_step.argtypes = [
            ctypes.c_long, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_float,
            ctypes.c_float, ctypes.c_long] + [_c_float_p] * 5 + [_c_u16_p]
        cdll.ds_lamb_step.restype = ctypes.c_float
        return cdll


class SparseLutBuilder(OpBuilder):
    """Builds the layout->LUT lowering op (reference
    op_builder/sparse_attn.py builds the OpenMP sdd_segment load balancer,
    csrc/sparse_attention/utils.cpp:119)."""

    BUILD_VAR = "DS_BUILD_SPARSE_ATTN"
    NAME = "sparse_lut"

    def __init__(self):
        super().__init__(self.NAME)

    def sources(self):
        return [csrc_path("sparse_attention", "lut.cpp")]

    def _bind(self, cdll):
        i32p = ctypes.POINTER(ctypes.c_int32)
        dims = [ctypes.c_long, ctypes.c_long, ctypes.c_long]
        cdll.ds_lut_max_degree.argtypes = dims + [i32p, ctypes.c_int]
        cdll.ds_lut_max_degree.restype = ctypes.c_long
        cdll.ds_build_lut.argtypes = dims + [i32p, ctypes.c_int,
                                             ctypes.c_long, i32p]
        cdll.ds_build_lut.restype = None
        return cdll


class UtilsBuilder(OpBuilder):
    """Builds flatten/unflatten (reference op_builder/utils.py)."""

    BUILD_VAR = "DS_BUILD_UTILS"
    NAME = "utils"

    def __init__(self):
        super().__init__(self.NAME)

    def sources(self):
        return [csrc_path("utils", "flatten_unflatten.cpp")]

    def _bind(self, cdll):
        pp = ctypes.POINTER(_c_float_p)
        cdll.ds_flatten.argtypes = [pp, _c_long_p, ctypes.c_int, _c_float_p]
        cdll.ds_flatten.restype = None
        cdll.ds_unflatten.argtypes = [pp, _c_long_p, ctypes.c_int, _c_float_p]
        cdll.ds_unflatten.restype = None
        return cdll

    @staticmethod
    def flatten_into(lib, dst, arrays):
        """Pack contiguous fp32 ``arrays`` into ``dst`` back-to-back with
        one OpenMP ds_flatten call. The ctypes marshaling lives here, next
        to the argtypes, so the ABI is spelled out in exactly one module."""
        srcs = (_c_float_p * len(arrays))(
            *[a.ctypes.data_as(_c_float_p) for a in arrays])
        sizes = (ctypes.c_long * len(arrays))(*[a.size for a in arrays])
        lib.ds_flatten(srcs, sizes, len(arrays),
                       dst.ctypes.data_as(_c_float_p))

    @staticmethod
    def unflatten_into(lib, dsts, src):
        """Scatter ``src`` back into contiguous fp32 ``dsts`` spans."""
        ptrs = (_c_float_p * len(dsts))(
            *[a.ctypes.data_as(_c_float_p) for a in dsts])
        sizes = (ctypes.c_long * len(dsts))(*[a.size for a in dsts])
        lib.ds_unflatten(ptrs, sizes, len(dsts),
                         src.ctypes.data_as(_c_float_p))
