"""ds_report — environment / op compatibility report
(reference deepspeed/env_report.py:23-50: prints the op install/compat matrix
and torch/cuda versions; here jax/libtpu and the TPU op registry).
"""

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
SUCCESS = GREEN + "[YES]" + END
WARNING = YELLOW + "[WARNING]" + END
FAIL = RED + "[NO]" + END
OKAY = GREEN + "[OKAY]" + END


def op_report():
    from deepspeed_tpu.op_builder import ALL_OPS
    max_dots = 23
    print("-" * 64)
    print("DeepSpeed-TPU ops report")
    print("-" * 64)
    print("op name" + "." * (max_dots - len("op name")) + "compatible")
    print("-" * 64)
    rows = []
    for op_name, builder_cls in ALL_OPS.items():
        builder = builder_cls()
        compat = builder.is_compatible()
        status = OKAY if compat else FAIL
        kind = "pallas" if not builder.sources() else "c++"
        line = "{} [{}]{}{}".format(
            op_name, kind, "." * max(max_dots - len(op_name) - len(kind) - 3,
                                     1), status)
        print(line)
        rows.append((op_name, kind, compat))
    print("-" * 64)
    return rows


def _probe_backend(timeout=30):
    """Backend info via a SUBPROCESS with a timeout: a wedged device
    relay blocks jax.devices() forever (try/except cannot catch a hang),
    and an environment report must never hang."""
    import subprocess
    import sys
    code = ("import jax; d = jax.devices(); "
            "print(jax.default_backend()); print(len(d)); "
            "print(d[0].device_kind if d else 'none')")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return None, "probe timed out after {}s (wedged relay?)".format(
            timeout)
    if r.returncode != 0:
        lines = (r.stderr or "").strip().splitlines()
        return None, (lines[-1] if lines else "error")
    lines = r.stdout.strip().splitlines()
    return lines, ""


def version_report():
    import os

    import jax
    import jaxlib
    print("DeepSpeed-TPU general environment info:")
    try:
        import deepspeed_tpu
        print("deepspeed install path ...", deepspeed_tpu.__path__)
        print("deepspeed info ...........", deepspeed_tpu.__version__)
    except Exception:
        pass
    print("jax version ..............", jax.__version__)
    print("jaxlib version ...........", jaxlib.__version__)
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
        lines, err = (["cpu", str(jax.device_count()), "cpu"], "")
    else:
        lines, err = _probe_backend()
    if lines:
        print("jax backend ..............", lines[0])
        print("device count .............", lines[1])
        print("device kind ..............", lines[2])
    else:
        print("jax backend ..............", "unavailable ({})".format(err))
    try:
        import flax
        print("flax version .............", flax.__version__)
    except ImportError:
        print("flax version .............", "not installed")


def tuning_report():
    """Kernel-tuning knobs and table status (the reference's analogue is
    the op compat matrix; these govern which TPU kernel paths run)."""
    import json
    import os
    print("kernel tuning:")
    print("flash backward path ......",
          os.environ.get("DS_TPU_FLASH_BWD", "auto"))
    print("xe head impl .............",
          os.environ.get("DS_TPU_XE_HEAD", "eager"))
    print("online autotune ..........",
          os.environ.get("DS_TPU_AUTOTUNE", "0"))
    try:
        from deepspeed_tpu.ops import autotuner
        with open(autotuner._BUNDLED_PATH) as f:
            n = len(json.load(f))
        print("autotune table entries ...", n)
    except Exception:
        print("autotune table entries ...", "none")


def main():
    op_report()
    version_report()
    tuning_report()


def cli_main():
    main()


if __name__ == "__main__":
    main()
