"""ds_report — environment / op compatibility report
(reference deepspeed/env_report.py:23-50: prints the op install/compat matrix
and torch/cuda versions; here jax/libtpu and the TPU op registry).
"""

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
SUCCESS = GREEN + "[YES]" + END
WARNING = YELLOW + "[WARNING]" + END
FAIL = RED + "[NO]" + END
OKAY = GREEN + "[OKAY]" + END


def op_report():
    from deepspeed_tpu.op_builder import ALL_OPS
    max_dots = 23
    print("-" * 64)
    print("DeepSpeed-TPU ops report")
    print("-" * 64)
    print("op name" + "." * (max_dots - len("op name")) + "compatible")
    print("-" * 64)
    rows = []
    for op_name, builder_cls in ALL_OPS.items():
        builder = builder_cls()
        compat = builder.is_compatible()
        status = OKAY if compat else FAIL
        kind = "pallas" if not builder.sources() else "c++"
        line = "{} [{}]{}{}".format(
            op_name, kind, "." * max(max_dots - len(op_name) - len(kind) - 3,
                                     1), status)
        print(line)
        rows.append((op_name, kind, compat))
    print("-" * 64)
    return rows


def version_report():
    import jax
    import jaxlib
    print("DeepSpeed-TPU general environment info:")
    try:
        import deepspeed_tpu
        print("deepspeed install path ...", deepspeed_tpu.__path__)
        print("deepspeed info ...........", deepspeed_tpu.__version__)
    except Exception:
        pass
    print("jax version ..............", jax.__version__)
    print("jaxlib version ...........", jaxlib.__version__)
    try:
        backend = jax.default_backend()
        devices = jax.devices()
        print("jax backend ..............", backend)
        print("device count .............", len(devices))
        print("device kind ..............",
              devices[0].device_kind if devices else "none")
    except Exception as e:  # no accelerator / no device grant
        print("jax backend ..............", "unavailable ({})".format(e))
    try:
        import flax
        print("flax version .............", flax.__version__)
    except ImportError:
        print("flax version .............", "not installed")


def main():
    op_report()
    version_report()


def cli_main():
    main()


if __name__ == "__main__":
    main()
