"""deepspeed_tpu — a TPU-native large-model training framework with the
capability surface of DeepSpeed v0.3.10, rebuilt on JAX/XLA/pjit/Pallas.

API façade mirrors reference deepspeed/__init__.py: ``initialize()`` returns
``(engine, optimizer, training_dataloader, lr_scheduler)``;
``add_config_arguments()`` injects the --deepspeed argparse group;
``init_distributed()`` boots the multi-host runtime (jax.distributed instead
of NCCL/torch.distributed).
"""

from deepspeed_tpu import moe  # noqa: F401
from deepspeed_tpu import ops  # noqa: F401
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing  # noqa: F401
from deepspeed_tpu.runtime.config import DeepSpeedConfig  # noqa: F401
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments
from deepspeed_tpu.utils.distributed import init_distributed  # noqa: F401
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.version import git_branch, git_hash, version as __version__

__git_hash__ = git_hash
__git_branch__ = git_branch

# Backwards compatibility with the old deepspeed.pt module structure
# (reference __init__.py:37-47).
import sys as _sys
import types as _types

from deepspeed_tpu.runtime import config as _rt_config, utils as _rt_utils
from deepspeed_tpu.runtime.fp16 import loss_scaler as _loss_scaler

pt = _types.ModuleType("pt", "dummy pt module for backwards compatability")
pt.deepspeed_utils = _rt_utils
pt.deepspeed_config = _rt_config
pt.loss_scaler = _loss_scaler
_sys.modules[__name__ + ".pt"] = pt
_sys.modules[__name__ + ".pt.deepspeed_utils"] = _rt_utils
_sys.modules[__name__ + ".pt.deepspeed_config"] = _rt_config
_sys.modules[__name__ + ".pt.loss_scaler"] = _loss_scaler


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config_params=None,
               mesh=None):
    """Initialize the DeepSpeed engine (reference deepspeed/__init__.py:50-139).

    Arguments keep the reference contract; ``model`` is a flax module (or any
    ``init``/``apply`` object), ``model_parameters`` the param pytree (or None
    for lazy init at first forward). A ``PipelineModule`` model selects the
    pipeline engine. Extra TPU-only kwarg: ``mesh`` to supply a prebuilt
    jax.sharding.Mesh.

    Returns: tuple of ``engine, optimizer, training_dataloader, lr_scheduler``.
    """
    log_dist("DeepSpeed info: version={}, git-hash={}, git-branch={}".format(
        __version__, git_hash, git_branch), ranks=[0])

    assert model is not None, "deepspeed.initialize requires a model"

    from deepspeed_tpu.pipe import PipelineModule
    if isinstance(model, PipelineModule):
        if getattr(model, "compiled", False):
            from deepspeed_tpu.runtime.pipe.compiled import (
                CompiledPipelineEngine as PipelineEngine)
        else:
            from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
        engine = PipelineEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mpu=model.mpu() if hasattr(model, "mpu") else mpu,
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn,
                                config_params=config_params,
                                mesh=mesh)
    else:
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 collate_fn=collate_fn,
                                 config_params=config_params,
                                 mesh=mesh)

    return_items = [
        engine,
        engine.optimizer,
        engine.training_dataloader,
        engine.lr_scheduler,
    ]
    return tuple(return_items)


def init_inference(model=None, params=None, config=None, mesh=None):
    """Initialize the serving engine (the reference's
    ``deepspeed.init_inference`` shape, which v0.3.10 does not have —
    its only inference surface is pipelined eval_batch).

    ``model`` is a GPT2LMHeadModel (or its config); ``params`` the trained
    pytree. ``config`` may be an ``InferenceConfig``, a bare ``inference``
    block dict, a full ds_config dict carrying an ``"inference"`` key, or
    a parsed ``DeepSpeedConfig``. Extra TPU-only kwarg: ``mesh`` — pass a
    mesh with a 'model' axis to serve a tensor-sharded model.

    Returns the ``InferenceEngine``.
    """
    from deepspeed_tpu.inference import InferenceConfig, InferenceEngine

    assert model is not None, "init_inference requires a model"
    assert params is not None, "init_inference requires trained params"
    if isinstance(config, DeepSpeedConfig):
        config = InferenceConfig.from_dict(config.inference)
    elif isinstance(config, dict) and "inference" in config:
        config = InferenceConfig.from_dict(config["inference"])
    return InferenceEngine(model, params, config=config, mesh=mesh)


def _add_core_arguments(parser):
    """Core DeepSpeed argparse group (reference __init__.py:142-190)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed",
                       default=False,
                       action="store_true",
                       help="Enable DeepSpeed (helper flag for user code, no "
                       "impact on DeepSpeed backend)")
    group.add_argument("--deepspeed_config",
                       default=None,
                       type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepscale",
                       default=False,
                       action="store_true",
                       help="Deprecated enable DeepSpeed (helper flag for user "
                       "code, no impact on DeepSpeed backend)")
    group.add_argument("--deepscale_config",
                       default=None,
                       type=str,
                       help="Deprecated DeepSpeed json configuration file.")
    return parser


def add_config_arguments(parser):
    """Update an argument parser to enable ds_config parsing
    (reference __init__.py:193-206)."""
    parser = _add_core_arguments(parser)
    return parser
