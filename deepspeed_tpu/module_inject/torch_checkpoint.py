"""Import torch-format (reference DeepSpeed / HuggingFace) checkpoints.

A user switching from the reference brings torch-serialized state:
either a DeepSpeed save directory (``mp_rank_XX_model_states.pt`` files
whose ``"module"`` entry is the torch ``state_dict()``, reference
engine.py:1521-1554) or a bare HF model state dict. This module converts
those into the flax param trees our models consume.

GPT-2 mapping notes (HF ``transformers`` GPT2LMHeadModel):
- our tree deliberately mirrors HF naming (wte, wpe, h_N/{ln_1, attn/
  {c_attn, c_proj}, ln_2, mlp/{c_fc, c_proj}}, ln_f), so the map is
  mostly mechanical;
- HF uses Conv1D whose weight is stored [in, out] — the same layout as a
  flax Dense kernel, so NO transpose (torch nn.Linear would need one);
- LayerNorm ``weight`` becomes flax ``scale``;
- ``lm_head.weight`` is tied to ``wte`` in both frameworks and is
  dropped on import.
"""

import os
import pickle
import re

import numpy as np

__all__ = [
    "load_torch_file",
    "import_bert_state_dict",
    "import_gpt2_state_dict",
    "import_reference_checkpoint",
]


def load_torch_file(path):
    """torch.load a checkpoint file and numpy-ify every tensor leaf.

    Accepts both torch's zipfile serialization (torch.save) and this
    repo's numpy-pickle files, so callers can point it at either
    lineage's ``mp_rank_XX_model_states.pt``."""
    try:
        import torch
    except ImportError:  # torch-less deployment: only our own files load
        torch = None
    if torch is not None:
        import zipfile
        if zipfile.is_zipfile(path):
            # A torch zipfile that torch.load rejects is corrupt — let
            # the original error surface instead of a confusing
            # second-stage pickle error from the fallback.
            obj = torch.load(path, map_location="cpu", weights_only=False)
            return _to_numpy(obj, torch)
        try:
            # Legacy (pre-zipfile) torch serialization has no cheap
            # magic check; attempt it, fall back to plain pickle.
            obj = torch.load(path, map_location="cpu", weights_only=False)
            return _to_numpy(obj, torch)
        except (pickle.UnpicklingError, RuntimeError, ValueError) as torch_err:
            try:
                with open(path, "rb") as f:
                    return pickle.load(f)
            except Exception as e:
                raise e from torch_err  # keep the torch error in the chain
    with open(path, "rb") as f:
        return pickle.load(f)


def _to_numpy(obj, torch):
    if isinstance(obj, torch.Tensor):
        return obj.detach().cpu().numpy()
    if isinstance(obj, dict):
        return {k: _to_numpy(v, torch) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy(v, torch) for v in obj)
    return obj


def _strip_prefixes(state_dict, prefixes=("module.", "transformer.")):
    """Drop wrapper prefixes ('module.' from DDP-style wrapping,
    'transformer.' from GPT2LMHeadModel) so keys start at the model
    root."""
    out = {}
    for key, val in state_dict.items():
        for pre in prefixes:
            if key.startswith(pre):
                key = key[len(pre):]
        out[key] = val
    return out


def import_gpt2_state_dict(state_dict, dtype=np.float32):
    """HF-style GPT-2 torch ``state_dict`` -> flax params tree for
    ``deepspeed_tpu.models.gpt2.GPT2LMHeadModel``.

    Returns a nested dict ready for ``model.apply({"params": ...})``.
    Raises KeyError on missing required entries (strict import — a
    silent partial load trains from garbage)."""
    sd = _strip_prefixes(state_dict)
    params = {
        "wte": np.asarray(sd["wte.weight"], dtype),
        "wpe": np.asarray(sd["wpe.weight"], dtype),
        "ln_f": {
            "scale": np.asarray(sd["ln_f.weight"], dtype),
            "bias": np.asarray(sd["ln_f.bias"], dtype),
        },
    }
    layer_ids = sorted({
        int(m.group(1))
        for m in (re.match(r"h\.(\d+)\.", k) for k in sd)
        if m
    })
    if not layer_ids:
        raise KeyError("no transformer blocks (h.N.*) in state dict")
    for i in layer_ids:
        pre = "h.{}.".format(i)
        params["h_{}".format(i)] = {
            "ln_1": {
                "scale": np.asarray(sd[pre + "ln_1.weight"], dtype),
                "bias": np.asarray(sd[pre + "ln_1.bias"], dtype),
            },
            "attn": {
                # HF Conv1D weight is [in, out] == flax Dense kernel.
                "c_attn": {
                    "kernel": np.asarray(sd[pre + "attn.c_attn.weight"],
                                         dtype),
                    "bias": np.asarray(sd[pre + "attn.c_attn.bias"], dtype),
                },
                "c_proj": {
                    "kernel": np.asarray(sd[pre + "attn.c_proj.weight"],
                                         dtype),
                    "bias": np.asarray(sd[pre + "attn.c_proj.bias"], dtype),
                },
            },
            "ln_2": {
                "scale": np.asarray(sd[pre + "ln_2.weight"], dtype),
                "bias": np.asarray(sd[pre + "ln_2.bias"], dtype),
            },
            "mlp": {
                "c_fc": {
                    "kernel": np.asarray(sd[pre + "mlp.c_fc.weight"], dtype),
                    "bias": np.asarray(sd[pre + "mlp.c_fc.bias"], dtype),
                },
                "c_proj": {
                    "kernel": np.asarray(sd[pre + "mlp.c_proj.weight"],
                                         dtype),
                    "bias": np.asarray(sd[pre + "mlp.c_proj.bias"], dtype),
                },
            },
        }
    return params


def import_bert_state_dict(state_dict, dtype=np.float32):
    """HF-style BERT torch ``state_dict`` (BertForPreTraining naming) ->
    flax params tree for ``deepspeed_tpu.models.bert.BertForPreTraining``
    with the FUSED encoder layout (use_fused_layer=True).

    torch Linear weights are [out, in] — exactly the packed fused-layer
    orientation (attn_qkvw = cat(q, k, v) along the out dim, reference
    replace_module.py:23-57) — so encoder weights copy without transpose;
    the flax Dense heads (pooler/transform/seq_relationship) DO
    transpose. ``cls.predictions.decoder.weight`` is tied to the word
    embeddings and dropped; ``cls.predictions.bias`` becomes mlm_bias."""
    sd = _strip_prefixes(state_dict, prefixes=("module.",))

    def arr(key):
        return np.asarray(sd[key], dtype)

    def linear_t(prefix):  # torch Linear -> flax Dense
        return {"kernel": arr(prefix + ".weight").T,
                "bias": arr(prefix + ".bias")}

    bert = {
        "embeddings": {
            "word_embeddings": arr("bert.embeddings.word_embeddings.weight"),
            "position_embeddings": arr(
                "bert.embeddings.position_embeddings.weight"),
            "token_type_embeddings": arr(
                "bert.embeddings.token_type_embeddings.weight"),
            "LayerNorm": {
                "scale": arr("bert.embeddings.LayerNorm.weight"),
                "bias": arr("bert.embeddings.LayerNorm.bias"),
            },
        },
        "pooler": linear_t("bert.pooler.dense"),
    }
    layer_ids = sorted({
        int(m.group(1))
        for m in (re.match(r"bert\.encoder\.layer\.(\d+)\.", k) for k in sd)
        if m
    })
    if not layer_ids:
        raise KeyError("no encoder layers (bert.encoder.layer.N.*) in "
                       "state dict")
    for i in layer_ids:
        pre = "bert.encoder.layer.{}.".format(i)
        bert["layer_{}".format(i)] = {
            "attn_qkvw": np.concatenate(
                [arr(pre + "attention.self.query.weight"),
                 arr(pre + "attention.self.key.weight"),
                 arr(pre + "attention.self.value.weight")], axis=0),
            "attn_qkvb": np.concatenate(
                [arr(pre + "attention.self.query.bias"),
                 arr(pre + "attention.self.key.bias"),
                 arr(pre + "attention.self.value.bias")]),
            "attn_ow": arr(pre + "attention.output.dense.weight"),
            "attn_ob": arr(pre + "attention.output.dense.bias"),
            "attn_nw": arr(pre + "attention.output.LayerNorm.weight"),
            "attn_nb": arr(pre + "attention.output.LayerNorm.bias"),
            "inter_w": arr(pre + "intermediate.dense.weight"),
            "inter_b": arr(pre + "intermediate.dense.bias"),
            "output_w": arr(pre + "output.dense.weight"),
            "output_b": arr(pre + "output.dense.bias"),
            "norm_w": arr(pre + "output.LayerNorm.weight"),
            "norm_b": arr(pre + "output.LayerNorm.bias"),
        }
    return {
        "bert": bert,
        "transform": linear_t("cls.predictions.transform.dense"),
        "transform_LayerNorm": {
            "scale": arr("cls.predictions.transform.LayerNorm.weight"),
            "bias": arr("cls.predictions.transform.LayerNorm.bias"),
        },
        "mlm_bias": arr("cls.predictions.bias"),
        "seq_relationship": linear_t("cls.seq_relationship"),
    }


def import_reference_checkpoint(load_dir, tag=None, mp_rank=0,
                                importer=import_gpt2_state_dict,
                                dtype=np.float32):
    """Load a reference-DeepSpeed save directory into a flax params tree.

    Reads ``latest`` when ``tag`` is None (reference engine.py:1293),
    then ``<tag>/mp_rank_XX_model_states.pt`` and converts its
    ``"module"`` state dict via ``importer``. Returns
    (params, client_state) where client_state carries the non-module
    checkpoint entries (global_steps, lr scheduler, ...)."""
    if tag is None:
        with open(os.path.join(load_dir, "latest")) as f:
            tag = f.read().strip()
    path = os.path.join(load_dir, tag,
                        "mp_rank_{:02d}_model_states.pt".format(mp_rank))
    ckpt = load_torch_file(path)
    module = ckpt.get("module")
    if module is None:
        raise KeyError("{} has no 'module' entry".format(path))
    params = importer(module, dtype=dtype)
    client = {k: v for k, v in ckpt.items() if k != "module"}
    return params, client
