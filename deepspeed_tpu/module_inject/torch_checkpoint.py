"""Import torch-format (reference DeepSpeed / HuggingFace) checkpoints.

A user switching from the reference brings torch-serialized state:
either a DeepSpeed save directory (``mp_rank_XX_model_states.pt`` files
whose ``"module"`` entry is the torch ``state_dict()``, reference
engine.py:1521-1554) or a bare HF model state dict. This module converts
those into the flax param trees our models consume.

GPT-2 mapping notes (HF ``transformers`` GPT2LMHeadModel):
- our tree deliberately mirrors HF naming (wte, wpe, h_N/{ln_1, attn/
  {c_attn, c_proj}, ln_2, mlp/{c_fc, c_proj}}, ln_f), so the map is
  mostly mechanical;
- HF uses Conv1D whose weight is stored [in, out] — the same layout as a
  flax Dense kernel, so NO transpose (torch nn.Linear would need one);
- LayerNorm ``weight`` becomes flax ``scale``;
- ``lm_head.weight`` is tied to ``wte`` in both frameworks and is
  dropped on import.
"""

import os
import pickle
import re

import numpy as np

__all__ = [
    "load_torch_file",
    "import_gpt2_state_dict",
    "import_reference_checkpoint",
]


def load_torch_file(path):
    """torch.load a checkpoint file and numpy-ify every tensor leaf.

    Accepts both torch's zipfile serialization (torch.save) and this
    repo's numpy-pickle files, so callers can point it at either
    lineage's ``mp_rank_XX_model_states.pt``."""
    try:
        import torch
    except ImportError:  # torch-less deployment: only our own files load
        torch = None
    if torch is not None:
        try:
            obj = torch.load(path, map_location="cpu", weights_only=False)
            return _to_numpy(obj, torch)
        except (pickle.UnpicklingError, RuntimeError, ValueError):
            pass  # not a torch zipfile — fall through to plain pickle
    with open(path, "rb") as f:
        return pickle.load(f)


def _to_numpy(obj, torch):
    if isinstance(obj, torch.Tensor):
        return obj.detach().cpu().numpy()
    if isinstance(obj, dict):
        return {k: _to_numpy(v, torch) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy(v, torch) for v in obj)
    return obj


def _strip_prefixes(state_dict):
    """Drop wrapper prefixes ('module.' from DDP-style wrapping,
    'transformer.' from GPT2LMHeadModel) so keys start at wte/h.N/ln_f."""
    out = {}
    for key, val in state_dict.items():
        for pre in ("module.", "transformer."):
            if key.startswith(pre):
                key = key[len(pre):]
        out[key] = val
    return out


def import_gpt2_state_dict(state_dict, dtype=np.float32):
    """HF-style GPT-2 torch ``state_dict`` -> flax params tree for
    ``deepspeed_tpu.models.gpt2.GPT2LMHeadModel``.

    Returns a nested dict ready for ``model.apply({"params": ...})``.
    Raises KeyError on missing required entries (strict import — a
    silent partial load trains from garbage)."""
    sd = _strip_prefixes(state_dict)
    params = {
        "wte": np.asarray(sd["wte.weight"], dtype),
        "wpe": np.asarray(sd["wpe.weight"], dtype),
        "ln_f": {
            "scale": np.asarray(sd["ln_f.weight"], dtype),
            "bias": np.asarray(sd["ln_f.bias"], dtype),
        },
    }
    layer_ids = sorted({
        int(m.group(1))
        for m in (re.match(r"h\.(\d+)\.", k) for k in sd)
        if m
    })
    if not layer_ids:
        raise KeyError("no transformer blocks (h.N.*) in state dict")
    for i in layer_ids:
        pre = "h.{}.".format(i)
        params["h_{}".format(i)] = {
            "ln_1": {
                "scale": np.asarray(sd[pre + "ln_1.weight"], dtype),
                "bias": np.asarray(sd[pre + "ln_1.bias"], dtype),
            },
            "attn": {
                # HF Conv1D weight is [in, out] == flax Dense kernel.
                "c_attn": {
                    "kernel": np.asarray(sd[pre + "attn.c_attn.weight"],
                                         dtype),
                    "bias": np.asarray(sd[pre + "attn.c_attn.bias"], dtype),
                },
                "c_proj": {
                    "kernel": np.asarray(sd[pre + "attn.c_proj.weight"],
                                         dtype),
                    "bias": np.asarray(sd[pre + "attn.c_proj.bias"], dtype),
                },
            },
            "ln_2": {
                "scale": np.asarray(sd[pre + "ln_2.weight"], dtype),
                "bias": np.asarray(sd[pre + "ln_2.bias"], dtype),
            },
            "mlp": {
                "c_fc": {
                    "kernel": np.asarray(sd[pre + "mlp.c_fc.weight"], dtype),
                    "bias": np.asarray(sd[pre + "mlp.c_fc.bias"], dtype),
                },
                "c_proj": {
                    "kernel": np.asarray(sd[pre + "mlp.c_proj.weight"],
                                         dtype),
                    "bias": np.asarray(sd[pre + "mlp.c_proj.bias"], dtype),
                },
            },
        }
    return params


def import_reference_checkpoint(load_dir, tag=None, mp_rank=0,
                                importer=import_gpt2_state_dict,
                                dtype=np.float32):
    """Load a reference-DeepSpeed save directory into a flax params tree.

    Reads ``latest`` when ``tag`` is None (reference engine.py:1293),
    then ``<tag>/mp_rank_XX_model_states.pt`` and converts its
    ``"module"`` state dict via ``importer``. Returns
    (params, client_state) where client_state carries the non-module
    checkpoint entries (global_steps, lr scheduler, ...)."""
    if tag is None:
        with open(os.path.join(load_dir, "latest")) as f:
            tag = f.read().strip()
    path = os.path.join(load_dir, tag,
                        "mp_rank_{:02d}_model_states.pt".format(mp_rank))
    ckpt = load_torch_file(path)
    module = ckpt.get("module")
    if module is None:
        raise KeyError("{} has no 'module' entry".format(path))
    params = importer(module, dtype=dtype)
    client = {k: v for k, v in ckpt.items() if k != "module"}
    return params, client
