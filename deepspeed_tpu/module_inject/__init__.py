from deepspeed_tpu.module_inject.replace_module import (
    pack_bert_layer, replace_attn_with_sparse, replace_module,
    replace_transformer_layer, revert_transformer_layer, unpack_bert_layer)
from deepspeed_tpu.module_inject.torch_checkpoint import (
    import_bert_state_dict, import_gpt2_state_dict,
    import_reference_checkpoint, load_torch_file)
