"""Module injection — swap stock transformer layers for the fused
DeepSpeedTransformerLayer (reference deepspeed/module_inject/
replace_module.py:6-192: recursive child swap on torch modules with QKV
weight re-packing, and the reverse).

Flax models are immutable module definitions + parameter pytrees, so the
TPU-native formulation is *param-tree surgery*: identify each HF-BERT-style
layer subtree in the params, re-pack its weights into the fused layer's
layout (QKV concatenated, [out, in] orientation), and apply the fused layer
with the re-packed tree. ``revert_transformer_layer`` inverts the packing
bit-exactly.
"""

import jax.numpy as jnp

from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer)


def _is_hf_bert_layer(tree):
    return (isinstance(tree, dict) and
            {"attention", "intermediate", "output"} <= set(tree.keys()))


def _is_ds_layer(tree):
    return (isinstance(tree, dict) and
            {"attn_qkvw", "inter_w", "norm_w"} <= set(tree.keys()))


def pack_bert_layer(layer):
    """HF flax BertLayer param subtree → DeepSpeedTransformerLayer params.

    The QKV concat mirrors the reference's weight re-packing
    (replace_module.py:23-57: qkvw = cat(q.w, k.w, v.w)). Flax Dense kernels
    are [in, out]; the fused layer stores [out, in] (y = x @ W.T).
    """
    att = layer["attention"]
    sa, ao = att["self"], att["output"]

    def wT(p):
        return jnp.transpose(p["kernel"])

    return {
        "attn_qkvw": jnp.concatenate(
            [wT(sa["query"]), wT(sa["key"]), wT(sa["value"])], axis=0),
        "attn_qkvb": jnp.concatenate(
            [sa["query"]["bias"], sa["key"]["bias"], sa["value"]["bias"]]),
        "attn_ow": wT(ao["dense"]),
        "attn_ob": ao["dense"]["bias"],
        "attn_nw": ao["LayerNorm"]["scale"],
        "attn_nb": ao["LayerNorm"]["bias"],
        "inter_w": wT(layer["intermediate"]["dense"]),
        "inter_b": layer["intermediate"]["dense"]["bias"],
        "output_w": wT(layer["output"]["dense"]),
        "output_b": layer["output"]["dense"]["bias"],
        "norm_w": layer["output"]["LayerNorm"]["scale"],
        "norm_b": layer["output"]["LayerNorm"]["bias"],
    }


def unpack_bert_layer(ds):
    """Inverse of :func:`pack_bert_layer` (reference revert_transformer_layer,
    replace_module.py:92-157)."""
    h = ds["attn_ow"].shape[0]
    if ds["attn_qkvw"].shape != (3 * h, h):
        raise ValueError("attn_qkvw shape {} inconsistent with hidden {}"
                         .format(ds["attn_qkvw"].shape, h))
    qw, kw, vw = jnp.split(ds["attn_qkvw"], 3, axis=0)
    qb, kb, vb = jnp.split(ds["attn_qkvb"], 3)

    def dense(w_out_in, b):
        return {"kernel": jnp.transpose(w_out_in), "bias": b}

    return {
        "attention": {
            "self": {
                "query": dense(qw, qb),
                "key": dense(kw, kb),
                "value": dense(vw, vb),
            },
            "output": {
                "dense": dense(ds["attn_ow"], ds["attn_ob"]),
                "LayerNorm": {"scale": ds["attn_nw"], "bias": ds["attn_nb"]},
            },
        },
        "intermediate": {"dense": dense(ds["inter_w"], ds["inter_b"])},
        "output": {
            "dense": dense(ds["output_w"], ds["output_b"]),
            "LayerNorm": {"scale": ds["norm_w"], "bias": ds["norm_b"]},
        },
    }


def replace_module(params, predicate, transform):
    """Generic recursive subtree swap (reference replace_module,
    replace_module.py:160-192): wherever ``predicate(subtree)`` holds,
    substitute ``transform(subtree)``; recurse elsewhere."""
    if predicate(params):
        return transform(params)
    if isinstance(params, dict):
        return {k: replace_module(v, predicate, transform)
                for k, v in params.items()}
    return params


def replace_transformer_layer(orig_layer_impl=None, model=None, params=None,
                              micro_batch_size=-1, bert_config=None,
                              seed=-1, max_seq_length=512, preln=False,
                              fp16=True, training=True):
    """Re-pack every HF-BERT layer subtree in ``params`` into fused-layer
    layout and return (fused_layer_module, new_params)
    (reference replace_transformer_layer, replace_module.py:6-89).

    ``bert_config`` needs hidden_size / num_attention_heads /
    intermediate_size / hidden_dropout_prob / attention_probs_dropout_prob
    (HF duck typing, as the reference).
    """
    if params is None:
        raise ValueError("params pytree is required (flax models carry "
                         "weights outside the module)")
    cfg = DeepSpeedTransformerConfig(
        batch_size=micro_batch_size,
        hidden_size=bert_config.hidden_size,
        intermediate_size=getattr(bert_config, "intermediate_size",
                                  4 * bert_config.hidden_size),
        heads=bert_config.num_attention_heads,
        attn_dropout_ratio=getattr(bert_config,
                                   "attention_probs_dropout_prob", 0.1),
        hidden_dropout_ratio=getattr(bert_config, "hidden_dropout_prob", 0.1),
        num_hidden_layers=getattr(bert_config, "num_hidden_layers", -1),
        seed=seed,
        fp16=fp16,
        pre_layer_norm=preln,
        training=training,
        dtype=jnp.float16 if fp16 else jnp.float32,
    )
    layer = DeepSpeedTransformerLayer(config=cfg)
    new_params = replace_module(params, _is_hf_bert_layer, pack_bert_layer)
    return layer, new_params


def revert_transformer_layer(orig_layer_impl=None, model=None, params=None,
                             config=None, preln=False):
    """Inverse swap: fused-layer subtrees → HF layout
    (reference replace_module.py:92-157)."""
    if params is None:
        raise ValueError("params pytree is required")
    return replace_module(params, _is_ds_layer, unpack_bert_layer)


def replace_attn_with_sparse(model, max_position, sparsity_config=None):
    """Swap a model's attention module class for BertSparseSelfAttention
    (SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention,
    reference sparse_attention_utils.py:85-121).

    Flax modules are frozen dataclasses, so the model must expose the
    attention implementation as a dataclass field (duck-typed:
    ``attention_module`` or ``attention_cls``); the swap is a
    ``dataclasses.replace``. Models that hard-code their attention raise with
    guidance, since there is no generic child-module mutation in flax.
    """
    import dataclasses
    from deepspeed_tpu.ops.sparse_attention import (BertSparseSelfAttention,
                                                    FixedSparsityConfig)
    for field in ("attention_module", "attention_cls"):
        if hasattr(model, field):
            sc = sparsity_config or FixedSparsityConfig(
                num_heads=getattr(model, "num_attention_heads", 4))
            return dataclasses.replace(model, **{
                field: lambda cfg: BertSparseSelfAttention(
                    config=cfg, sparsity_config=sc)})
    raise TypeError(
        "model {} does not expose an 'attention_module'/'attention_cls' "
        "field; construct it with BertSparseSelfAttention directly (flax "
        "modules cannot be mutated in place)".format(type(model).__name__))
